"""``paddle.distribution`` — probability distributions.

Reference: python/paddle/distribution/ (Distribution base, Normal,
Uniform, Categorical, Beta, Dirichlet, kl_divergence registry in kl.py).

TPU-native: sampling draws from the framework RNG (functional PRNG keys),
log_prob/entropy are closed-form jnp expressions — all jit-traceable.
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Tuple, Type

import numpy as np

from ..framework import random as _random
from ..framework.tensor import Tensor

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Beta",
           "Dirichlet", "kl_divergence", "register_kl",
           "ExponentialFamily", "Independent", "Multinomial",
           "TransformedDistribution"]


def _arr(x):
    import jax.numpy as jnp
    if isinstance(x, Tensor):
        return x._data.astype(jnp.float32)
    return jnp.asarray(x, jnp.float32)


class Distribution:
    """Reference distribution/distribution.py Distribution base."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ..framework.dispatch import call_op
        return call_op("exp", self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


def _draw_key(seed):
    """seed=0 (the reference default) draws from the global stream; an
    explicit nonzero seed gives a reproducible dedicated stream."""
    import jax
    if seed:
        return jax.random.key(int(seed))
    return _random.next_key()


class Normal(Distribution):
    """Reference distribution/normal.py."""

    def __init__(self, loc, scale, name=None):
        # keep original Tensor params so rsample stays differentiable
        self._loc_t = loc if isinstance(loc, Tensor) else None
        self._scale_t = scale if isinstance(scale, Tensor) else None
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        import jax.numpy as jnp
        shape = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return Tensor(self.loc)

    @property
    def variance(self):
        return Tensor(self.scale ** 2)

    def sample(self, shape=(), seed=0):
        import jax
        key = _draw_key(seed)
        out = self.loc + self.scale * jax.random.normal(
            key, tuple(shape) + self.batch_shape)
        return Tensor(out)

    def rsample(self, shape=(), seed=0):
        """Reparameterized draw: differentiable w.r.t. Tensor loc/scale
        (loc + scale * eps) — feeds VAE/policy-gradient training."""
        import jax
        from .. import autograd
        key = _draw_key(seed)
        eps = jax.random.normal(key, tuple(shape) + self.batch_shape)
        loc_t = self._loc_t if self._loc_t is not None else \
            Tensor(self.loc)
        scale_t = self._scale_t if self._scale_t is not None else \
            Tensor(self.scale)
        return autograd.differentiable_apply(
            lambda l, s: l + s * eps, loc_t, scale_t)

    def log_prob(self, value):
        import jax.numpy as jnp
        v = _arr(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale)
                      - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        import jax.numpy as jnp
        ent = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return Tensor(jnp.broadcast_to(ent, self.batch_shape))

    def probs(self, value):
        return self.prob(value)


class Uniform(Distribution):
    """Reference distribution/uniform.py: U[low, high)."""

    def __init__(self, low, high, name=None):
        self._low_t = low if isinstance(low, Tensor) else None
        self._high_t = high if isinstance(high, Tensor) else None
        self.low = _arr(low)
        self.high = _arr(high)
        import jax.numpy as jnp
        shape = jnp.broadcast_shapes(self.low.shape, self.high.shape)
        super().__init__(batch_shape=shape)

    def sample(self, shape=(), seed=0):
        import jax
        key = _draw_key(seed)
        u = jax.random.uniform(key, tuple(shape) + self.batch_shape)
        return Tensor(self.low + u * (self.high - self.low))

    def rsample(self, shape=(), seed=0):
        import jax
        from .. import autograd
        key = _draw_key(seed)
        u = jax.random.uniform(key, tuple(shape) + self.batch_shape)
        low_t = self._low_t if self._low_t is not None else \
            Tensor(self.low)
        high_t = self._high_t if self._high_t is not None else \
            Tensor(self.high)
        return autograd.differentiable_apply(
            lambda lo, hi: lo + u * (hi - lo), low_t, high_t)

    def log_prob(self, value):
        import jax.numpy as jnp
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        import jax.numpy as jnp
        return Tensor(jnp.log(self.high - self.low))


class Categorical(Distribution):
    """Reference distribution/categorical.py (constructed from logits)."""

    def __init__(self, logits, name=None):
        import jax
        import jax.numpy as jnp
        self.logits = _arr(logits)
        self._log_p = jax.nn.log_softmax(self.logits, axis=-1)
        super().__init__(batch_shape=self.logits.shape[:-1])

    @property
    def probs_tensor(self):
        import jax.numpy as jnp
        return Tensor(jnp.exp(self._log_p))

    def sample(self, shape=(), seed=0):
        import jax
        key = _draw_key(seed)
        out = jax.random.categorical(
            key, self.logits, shape=tuple(shape) + self.batch_shape)
        return Tensor(out)

    def log_prob(self, value):
        import jax.numpy as jnp
        v = _arr(value).astype(jnp.int32)
        return Tensor(jnp.take_along_axis(
            self._log_p, v[..., None], axis=-1)[..., 0])

    def probs(self, value):
        return self.prob(value)

    def entropy(self):
        import jax.numpy as jnp
        p = jnp.exp(self._log_p)
        return Tensor(-(p * self._log_p).sum(-1))


class ExponentialFamily(Distribution):
    """Exponential-family base: p(x) = h(x) exp(<η, T(x)> − A(η)).

    Reference: distribution/exponential_family.py — entropy via the
    Bregman divergence of the log-normalizer. TPU-native: the reference
    hand-rolls the gradient through its autograd; here ``jax.grad`` of
    ``_log_normalizer`` w.r.t. the natural parameters IS the expected
    sufficient statistic, so the generic entropy/KL need no per-family
    math."""

    @property
    def _natural_parameters(self) -> tuple:
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        """−E[log p] = A(η) − <η, ∇A(η)> + E[−log h] (Bregman form)."""
        import jax
        import jax.numpy as jnp
        nparams = tuple(jnp.asarray(p) for p in self._natural_parameters)
        lognorm = self._log_normalizer(*nparams)
        grads = jax.grad(
            lambda ps: jnp.sum(self._log_normalizer(*ps)))(nparams)
        ent = lognorm + jnp.asarray(self._mean_carrier_measure)
        for np_, g in zip(nparams, grads):
            ent = ent - (np_ * g).reshape(
                np_.shape[:lognorm.ndim] + (-1,)).sum(-1) \
                if np_.ndim > lognorm.ndim else ent - np_ * g
        return Tensor(ent)


class Beta(ExponentialFamily):
    """Reference distribution/beta.py."""

    def __init__(self, alpha, beta):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)
        import jax.numpy as jnp
        shape = jnp.broadcast_shapes(self.alpha.shape, self.beta.shape)
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def _natural_parameters(self):
        return (self.alpha, self.beta)

    def _log_normalizer(self, a, b):
        import jax.scipy.special as jsp
        return jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b)

    def sample(self, shape=(), seed=0):
        import jax
        key = _draw_key(seed)
        return Tensor(jax.random.beta(
            key, self.alpha, self.beta, tuple(shape) + self.batch_shape))

    def log_prob(self, value):
        import jax.scipy.special as jsp
        import jax.numpy as jnp
        v = _arr(value)
        lbeta = (jsp.gammaln(self.alpha) + jsp.gammaln(self.beta)
                 - jsp.gammaln(self.alpha + self.beta))
        return Tensor((self.alpha - 1) * jnp.log(v)
                      + (self.beta - 1) * jnp.log1p(-v) - lbeta)

    def entropy(self):
        import jax.scipy.special as jsp
        a, b = self.alpha, self.beta
        lbeta = (jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b))
        return Tensor(lbeta - (a - 1) * jsp.digamma(a)
                      - (b - 1) * jsp.digamma(b)
                      + (a + b - 2) * jsp.digamma(a + b))


class Dirichlet(ExponentialFamily):
    """Reference distribution/dirichlet.py."""

    def __init__(self, concentration):
        self.concentration = _arr(concentration)
        super().__init__(batch_shape=self.concentration.shape[:-1],
                         event_shape=self.concentration.shape[-1:])

    @property
    def _natural_parameters(self):
        return (self.concentration,)

    def _log_normalizer(self, a):
        import jax.scipy.special as jsp
        return jsp.gammaln(a).sum(-1) - jsp.gammaln(a.sum(-1))

    def sample(self, shape=(), seed=0):
        import jax
        key = _draw_key(seed)
        return Tensor(jax.random.dirichlet(
            key, self.concentration, tuple(shape) + self.batch_shape))

    def log_prob(self, value):
        import jax.scipy.special as jsp
        import jax.numpy as jnp
        v = _arr(value)
        a = self.concentration
        norm = jsp.gammaln(a.sum(-1)) - jsp.gammaln(a).sum(-1)
        return Tensor(((a - 1) * jnp.log(v)).sum(-1) + norm)

    def entropy(self):
        import jax.scipy.special as jsp
        a = self.concentration
        a0 = a.sum(-1)
        k = a.shape[-1]
        lnB = jsp.gammaln(a).sum(-1) - jsp.gammaln(a0)
        return Tensor(lnB + (a0 - k) * jsp.digamma(a0)
                      - ((a - 1) * jsp.digamma(a)).sum(-1))


class Independent(Distribution):
    """Reinterpret the rightmost ``reinterpreted_batch_rank`` batch dims
    as event dims (reference distribution/independent.py)."""

    def __init__(self, base: Distribution, reinterpreted_batch_rank: int):
        if not (0 < reinterpreted_batch_rank <= len(base.batch_shape)):
            raise ValueError(
                f"reinterpreted_batch_rank must be in (0, "
                f"{len(base.batch_shape)}], got {reinterpreted_batch_rank}")
        self._base = base
        self._reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        shape = base.batch_shape + base.event_shape
        split = len(base.batch_shape) - reinterpreted_batch_rank
        super().__init__(batch_shape=shape[:split],
                         event_shape=shape[split:])

    @property
    def mean(self):
        return self._base.mean

    @property
    def variance(self):
        return self._base.variance

    def sample(self, shape=(), seed=0):
        return self._base.sample(shape, seed=seed)

    def rsample(self, shape=(), seed=0):
        return self._base.rsample(shape, seed=seed)

    def _sum_rightmost(self, value, n):
        return value.sum(tuple(range(-n, 0))) if n > 0 else value

    def log_prob(self, value):
        lp = self._base.log_prob(value)
        return Tensor(self._sum_rightmost(
            lp._data, self._reinterpreted_batch_rank))

    def entropy(self):
        ent = self._base.entropy()
        return Tensor(self._sum_rightmost(
            ent._data, self._reinterpreted_batch_rank))


class Multinomial(Distribution):
    """Counts over k categories from ``total_count`` independent draws
    (reference distribution/multinomial.py)."""

    def __init__(self, total_count, probs):
        import jax.numpy as jnp
        if not isinstance(total_count, int) or total_count < 1:
            raise ValueError("total_count must be an int >= 1")
        p = _arr(probs)
        if p.ndim < 1:
            raise ValueError("probs must have at least one dimension")
        self.probs = p / p.sum(-1, keepdims=True)
        self.total_count = total_count
        self._categorical = Categorical(jnp.log(self.probs))
        super().__init__(batch_shape=p.shape[:-1],
                         event_shape=p.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.probs * self.total_count)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs * (1 - self.probs))

    def log_prob(self, value):
        import jax.numpy as jnp
        import jax.scipy.special as jsp
        v = _arr(value)
        logits = jnp.log(self.probs)
        # 0 * log(0) := 0 for impossible-but-unused categories
        logits = jnp.where((v == 0) & jnp.isneginf(logits), 0.0, logits)
        return Tensor(jsp.gammaln(v.sum(-1) + 1)
                      - jsp.gammaln(v + 1).sum(-1)
                      + (v * logits).sum(-1))

    def sample(self, shape=(), seed=0):
        import jax
        import jax.numpy as jnp
        key = _draw_key(seed)
        draws = jax.random.categorical(
            key, jnp.log(self.probs),
            shape=(self.total_count,) + tuple(shape) + self.batch_shape)
        onehot = jax.nn.one_hot(draws, self.probs.shape[-1],
                                dtype=self.probs.dtype)
        return Tensor(onehot.sum(0))

    def entropy(self):
        import jax.numpy as jnp
        import jax.scipy.special as jsp
        n = float(self.total_count)
        # H = n*H(cat) - lgamma(n+1) + sum_i E[lgamma(X_i + 1)] with
        # X_i ~ Binomial(n, p_i) (reference multinomial.py:154)
        support = jnp.arange(1.0, n + 1)
        shape = (-1,) + (1,) * self.probs.ndim
        support = support.reshape(shape)
        logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        norm = (n * jnp.clip(logits, 0)
                + n * jnp.log1p(jnp.exp(-jnp.abs(logits)))
                - jsp.gammaln(n + 1))
        binom_lp = (support * logits - jsp.gammaln(support + 1)
                    - jsp.gammaln(n - support + 1) - norm)
        e_lgamma = (jnp.exp(binom_lp)
                    * jsp.gammaln(support + 1)).sum(0).sum(-1)
        cat_ent = self._categorical.entropy()._data
        return Tensor(n * cat_ent - jsp.gammaln(n + 1) + e_lgamma)


class TransformedDistribution(Distribution):
    """base distribution pushed through a transform chain (reference
    distribution/transformed_distribution.py)."""

    def __init__(self, base: Distribution, transforms):
        from .transform import ChainTransform, Transform
        if isinstance(transforms, Transform):
            transforms = [transforms]
        for t in transforms:
            if not isinstance(t, Transform):
                raise TypeError(f"not a Transform: {t!r}")
        self._base = base
        self.transforms = list(transforms)
        chain = ChainTransform(self.transforms)
        base_shape = base.batch_shape + base.event_shape
        out_shape = chain.forward_shape(base_shape)
        event_rank = max(chain._codomain_event_rank,
                         len(base.event_shape))
        split = len(out_shape) - event_rank
        super().__init__(batch_shape=tuple(out_shape[:split]),
                         event_shape=tuple(out_shape[split:]))

    def sample(self, shape=(), seed=0):
        x = self._base.sample(shape, seed=seed)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=(), seed=0):
        x = self._base.rsample(shape, seed=seed)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        import jax.numpy as jnp

        def sum_rightmost(v, n):
            return v.sum(tuple(range(-n, 0))) if n > 0 else v

        y = _arr(value)
        event_rank = len(self.event_shape)
        lp = 0.0
        for t in reversed(self.transforms):
            if not t._is_injective():
                raise NotImplementedError(
                    f"log_prob through non-injective "
                    f"{type(t).__name__} is undefined")
            x = t._inverse(y)
            ldj = jnp.asarray(t.forward_log_det_jacobian(Tensor(x))._data)
            lp = lp - sum_rightmost(
                ldj, event_rank - t._codomain_event_rank)
            event_rank += t._domain_event_rank - t._codomain_event_rank
            y = x
        base_lp = jnp.asarray(self._base.log_prob(Tensor(y))._data)
        lp = lp + sum_rightmost(
            base_lp, event_rank - len(self._base.event_shape))
        return Tensor(lp)


from .transform import (  # noqa: E402,F401
    AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform,
    SigmoidTransform, SoftmaxTransform, StackTransform,
    StickBreakingTransform, TanhTransform, Transform)

__all__ += [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform"]


# ---------------------------------------------------------------------------
# KL divergence registry (reference distribution/kl.py)
# ---------------------------------------------------------------------------

_KL_REGISTRY: Dict[Tuple[Type, Type], callable] = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            return fn(p, q)
    raise NotImplementedError(
        f"no KL rule for ({type(p).__name__}, {type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    import jax.numpy as jnp
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    import jax.numpy as jnp
    r = jnp.log((q.high - q.low) / (p.high - p.low))
    outside = (p.low < q.low) | (p.high > q.high)
    return Tensor(jnp.where(outside, jnp.inf, r))


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    import jax.numpy as jnp
    pp = jnp.exp(p._log_p)
    return Tensor((pp * (p._log_p - q._log_p)).sum(-1))


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    import jax.scipy.special as jsp
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    lbeta1 = jsp.gammaln(a1) + jsp.gammaln(b1) - jsp.gammaln(a1 + b1)
    lbeta2 = jsp.gammaln(a2) + jsp.gammaln(b2) - jsp.gammaln(a2 + b2)
    return Tensor(lbeta2 - lbeta1
                  + (a1 - a2) * jsp.digamma(a1)
                  + (b1 - b2) * jsp.digamma(b1)
                  + (a2 - a1 + b2 - b1) * jsp.digamma(a1 + b1))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    import jax.scipy.special as jsp
    a, b = p.concentration, q.concentration
    a0 = a.sum(-1)
    lnB_a = jsp.gammaln(a).sum(-1) - jsp.gammaln(a0)
    lnB_b = jsp.gammaln(b).sum(-1) - jsp.gammaln(b.sum(-1))
    return Tensor(lnB_b - lnB_a
                  + ((a - b) * (jsp.digamma(a)
                                - jsp.digamma(a0)[..., None])).sum(-1))


@register_kl(Independent, Independent)
def _kl_independent_independent(p, q):
    if p._reinterpreted_batch_rank != q._reinterpreted_batch_rank:
        raise NotImplementedError(
            "KL between Independents of different reinterpreted ranks")
    kl = kl_divergence(p._base, q._base)
    return Tensor(p._sum_rightmost(kl._data, p._reinterpreted_batch_rank))


@register_kl(ExponentialFamily, ExponentialFamily)
def _kl_expfamily_expfamily(p, q):
    """Generic same-family KL via the Bregman divergence of the
    log-normalizer (reference kl.py _kl_expfamily_expfamily — there via
    hand-rolled double grad, here one jax.value_and_grad)."""
    if type(p) is not type(q):
        raise NotImplementedError(
            f"KL between different families {type(p).__name__} and "
            f"{type(q).__name__}")
    import jax
    import jax.numpy as jnp
    p_np = tuple(jnp.asarray(v) for v in p._natural_parameters)
    q_np = tuple(jnp.asarray(v) for v in q._natural_parameters)
    grads = jax.grad(lambda ps: jnp.sum(p._log_normalizer(*ps)))(p_np)
    # KL = A(η_q) - A(η_p) - <η_q - η_p, ∇A(η_p)>
    kl = q._log_normalizer(*q_np) - p._log_normalizer(*p_np)
    for pn, qn, g in zip(p_np, q_np, grads):
        term = (pn - qn) * g
        extra = term.ndim - kl.ndim
        if extra > 0:
            term = term.sum(tuple(range(-extra, 0)))
        kl = kl + term
    return Tensor(kl)
