"""``paddle.distribution`` transforms.

Reference: python/paddle/distribution/transform.py (Transform base + 12
concrete transforms feeding TransformedDistribution) and variable.py
(domain/codomain declarations).

TPU-native: transforms are pure jnp expressions over arrays with Tensors
at the API boundary — fully jit-traceable, log-det-jacobians in closed
form.
"""
from __future__ import annotations

import enum
import functools
import math
import operator
from typing import Sequence

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["Transform", "AbsTransform", "AffineTransform", "ChainTransform",
           "ExpTransform", "IndependentTransform", "PowerTransform",
           "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
           "StackTransform", "StickBreakingTransform", "TanhTransform"]


def _a(x):
    if isinstance(x, Tensor):
        return x._data.astype(jnp.float32)
    return jnp.asarray(x, jnp.float32)


def _t(a):
    return Tensor(a)


class Type(enum.Enum):
    """Mapping type of a transform (reference transform.py Type)."""
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"

    @classmethod
    def is_injective(cls, t):
        return t in (cls.BIJECTION, cls.INJECTION)


class Transform:
    """Base transform: y = f(x) with log|det J| bookkeeping (reference
    transform.py Transform)."""

    _type = Type.INJECTION
    # event ranks consumed/produced (the variable.py domain/codomain
    # event_rank collapsed to the two integers the math needs)
    _domain_event_rank = 0
    _codomain_event_rank = 0

    @classmethod
    def _is_injective(cls):
        return Type.is_injective(cls._type)

    def __call__(self, x):
        from .import Distribution, TransformedDistribution
        if isinstance(x, Distribution):
            return TransformedDistribution(x, [self])
        if isinstance(x, Transform):
            return ChainTransform([self, x])
        return self.forward(x)

    def forward(self, x):
        return _t(self._forward(_a(x)))

    def inverse(self, y):
        return _t(self._inverse(_a(y)))

    def forward_log_det_jacobian(self, x):
        x = _a(x)
        if hasattr(self, "_forward_log_det_jacobian"):
            return _t(self._forward_log_det_jacobian(x))
        if hasattr(self, "_inverse_log_det_jacobian"):
            return _t(-self._inverse_log_det_jacobian(self._forward(x)))
        raise NotImplementedError(
            f"{type(self).__name__} has no log det jacobian")

    def inverse_log_det_jacobian(self, y):
        y = _a(y)
        if hasattr(self, "_inverse_log_det_jacobian"):
            return _t(self._inverse_log_det_jacobian(y))
        if hasattr(self, "_forward_log_det_jacobian"):
            return _t(-self._forward_log_det_jacobian(self._inverse(y)))
        raise NotImplementedError(
            f"{type(self).__name__} has no log det jacobian")

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)


class AbsTransform(Transform):
    """y = |x| — a surjection; ``inverse`` returns the positive branch
    (reference transform.py:318)."""

    _type = Type.SURJECTION

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y


class AffineTransform(Transform):
    """y = loc + scale * x (reference transform.py:390)."""

    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        self.loc = _a(loc)
        self.scale = _a(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ChainTransform(Transform):
    """Composition t_n ∘ … ∘ t_1 (reference transform.py:467)."""

    def __init__(self, transforms: Sequence[Transform]):
        self.transforms = list(transforms)
        self._type = (Type.BIJECTION if all(
            t._is_injective() for t in self.transforms)
            else Type.OTHER)

    @classmethod
    def _class_is_injective(cls):
        return True

    def _is_injective(self):
        return all(t._is_injective() for t in self.transforms)

    @property
    def _domain_event_rank(self):
        return max((t._domain_event_rank for t in self.transforms),
                   default=0)

    @property
    def _codomain_event_rank(self):
        return max((t._codomain_event_rank for t in self.transforms),
                   default=0)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        ldj = jnp.zeros(())
        for t in self.transforms:
            ldj = ldj + jnp.asarray(t.forward_log_det_jacobian(
                _t(x))._data)
            x = t._forward(x)
        return ldj

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return tuple(shape)

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return tuple(shape)


class ExpTransform(Transform):
    """y = exp(x) (reference transform.py:590)."""

    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class IndependentTransform(Transform):
    """Reinterpret the rightmost ``reinterpreted_batch_rank`` batch dims
    of ``base`` as event dims — jacobians sum over them (reference
    transform.py:639)."""

    def __init__(self, base: Transform, reinterpreted_batch_rank: int):
        if reinterpreted_batch_rank <= 0:
            raise ValueError("reinterpreted_batch_rank must be positive")
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        self._type = base._type

    @property
    def _domain_event_rank(self):
        return self.base._domain_event_rank + self.reinterpreted_batch_rank

    @property
    def _codomain_event_rank(self):
        return (self.base._codomain_event_rank
                + self.reinterpreted_batch_rank)

    def _is_injective(self):
        return self.base._is_injective()

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        ldj = jnp.asarray(
            self.base.forward_log_det_jacobian(_t(x))._data)
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))
        return ldj.sum(axes)

    def forward_shape(self, shape):
        return self.base.forward_shape(shape)

    def inverse_shape(self, shape):
        return self.base.inverse_shape(shape)


class PowerTransform(Transform):
    """y = x ** power on the positive reals (reference
    transform.py:730)."""

    _type = Type.BIJECTION

    def __init__(self, power):
        self.power = _a(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class ReshapeTransform(Transform):
    """Reshape the event part (reference transform.py:793)."""

    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        if functools.reduce(operator.mul, self.in_event_shape, 1) != \
                functools.reduce(operator.mul, self.out_event_shape, 1):
            raise ValueError(
                "in_event_shape and out_event_shape must have the same "
                "number of elements")

    @property
    def _domain_event_rank(self):
        return len(self.in_event_shape)

    @property
    def _codomain_event_rank(self):
        return len(self.out_event_shape)

    def _batch(self, shape, event):
        n = len(event)
        if n and tuple(shape[-n:]) != tuple(event):
            raise ValueError(
                f"trailing dims of {tuple(shape)} do not match {event}")
        return shape[:len(shape) - n] if n else shape

    def _forward(self, x):
        batch = self._batch(x.shape, self.in_event_shape)
        return x.reshape(tuple(batch) + self.out_event_shape)

    def _inverse(self, y):
        batch = self._batch(y.shape, self.out_event_shape)
        return y.reshape(tuple(batch) + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = self._batch(x.shape, self.in_event_shape)
        return jnp.zeros(batch, x.dtype)

    def forward_shape(self, shape):
        return tuple(self._batch(tuple(shape), self.in_event_shape)) \
            + self.out_event_shape

    def inverse_shape(self, shape):
        return tuple(self._batch(tuple(shape), self.out_event_shape)) \
            + self.in_event_shape


class SigmoidTransform(Transform):
    """y = sigmoid(x) (reference transform.py:900)."""

    _type = Type.BIJECTION

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class SoftmaxTransform(Transform):
    """y = softmax(x): a surjection onto the simplex with no density
    (reference transform.py:943)."""

    _type = Type.OTHER
    _domain_event_rank = 1
    _codomain_event_rank = 1

    def _forward(self, x):
        z = x - x.max(-1, keepdims=True)
        ez = jnp.exp(z)
        return ez / ez.sum(-1, keepdims=True)

    def _inverse(self, y):
        return jnp.log(y)


class StackTransform(Transform):
    """Apply a sequence of transforms to slices along ``axis``
    (reference transform.py:999)."""

    def __init__(self, transforms: Sequence[Transform], axis: int = 0):
        if not transforms:
            raise ValueError("transforms must not be empty")
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _is_injective(self):
        return all(t._is_injective() for t in self.transforms)

    def _map(self, method, v):
        parts = [
            getattr(t, method)(jnp.take(v, i, axis=self.axis))
            for i, t in enumerate(self.transforms)]
        return jnp.stack(parts, axis=self.axis)

    def _forward(self, x):
        return self._map("_forward", x)

    def _inverse(self, y):
        return self._map("_inverse", y)

    def _forward_log_det_jacobian(self, x):
        parts = [
            jnp.asarray(t.forward_log_det_jacobian(
                _t(jnp.take(x, i, axis=self.axis)))._data)
            for i, t in enumerate(self.transforms)]
        return jnp.stack(parts, axis=self.axis)


class StickBreakingTransform(Transform):
    """R^(K-1) -> K-simplex via stick-breaking (reference
    transform.py:1104)."""

    _type = Type.BIJECTION
    _domain_event_rank = 1
    _codomain_event_rank = 1

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        z = jax.nn.sigmoid(x - offset)
        zc = jnp.cumprod(1 - z, axis=-1)
        lead = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype), zc], axis=-1)
        pad_z = jnp.concatenate(
            [z, jnp.ones(x.shape[:-1] + (1,), x.dtype)], axis=-1)
        return lead * pad_z

    def _inverse(self, y):
        k = y.shape[-1] - 1
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=y.dtype))
        zc = 1 - jnp.cumsum(y[..., :-1], axis=-1)
        lead = jnp.concatenate(
            [jnp.ones(y.shape[:-1] + (1,), y.dtype), zc[..., :-1]],
            axis=-1)
        z = y[..., :-1] / lead
        return jnp.log(z) - jnp.log1p(-z) + offset

    def _forward_log_det_jacobian(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        z = x - offset
        # d y_i / d x_i factors: sigmoid'(z) * prod_{j<i}(1 - sig(z_j))
        zc_log = jnp.cumsum(jax.nn.log_sigmoid(-z), axis=-1)
        lead = jnp.concatenate(
            [jnp.zeros(x.shape[:-1] + (1,), x.dtype), zc_log[..., :-1]],
            axis=-1)
        return (jax.nn.log_sigmoid(z) + jax.nn.log_sigmoid(-z)
                + lead).sum(-1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class TanhTransform(Transform):
    """y = tanh(x) (reference transform.py:1169)."""

    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # numerically-stable 2(log2 - x - softplus(-2x))
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))
