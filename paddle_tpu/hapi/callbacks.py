"""hapi callbacks.

Analog of the reference's ``python/paddle/hapi/callbacks.py`` (ProgBarLogger,
ModelCheckpoint:534, EarlyStopping:690, LRScheduler:599, History).

Windowed-log contract (async fit path): ``Model.fit`` keeps loss/metrics
on device and flushes to the host once per ``log_freq`` steps, so the
``logs`` dict passed to ``on_train_batch_end`` updates at flush steps
(``step % log_freq == 0``) and holds the last flushed values in between
— aligned with ProgBarLogger's print cadence, which is why per-step
consumers see no staleness at default settings. Epoch-end hooks always
receive freshly flushed values.
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "ReduceLROnPlateau", "ProfilerCallback",
           "LRScheduler", "History", "VisualDL", "config_callbacks"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_train_abort(self):
        """Teardown when fit raises: release resources/global state
        WITHOUT the success-path side effects of on_train_end. Exceptions
        raised here are swallowed by Model.fit so they can never mask the
        training error."""
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return dispatch
        raise AttributeError(name)

    def on_train_abort(self):
        """Error-isolated teardown fan-out (unlike the generic on_*
        dispatch): when fit fails, EVERY callback's abort hook runs even
        if an earlier one raises, so e.g. ProfilerCallback's armed global
        session is always released."""
        for c in self.callbacks:
            try:
                c.on_train_abort()
            except Exception:
                pass


class ProgBarLogger(Callback):
    """Prints every key in the flush-window ``logs`` dict: loss and
    metrics always; ``mfu:`` when a device peak is known (PR 7); with
    ``fit(numerics=...)`` armed additionally ``grad_norm:`` (and
    ``loss_scale:`` when a GradScaler is active) from the numerics
    audit — all 0-d-scalar-coerced by :meth:`_fmt` exactly like loss,
    so a user forwarding unflushed device values still gets numbers,
    not array reprs."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()
        if self.verbose and self.params.get("epochs"):
            print(f"Epoch {epoch + 1}/{self.params['epochs']}")

    def _fmt(self, logs):
        parts = []
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple, np.ndarray)):
                parts.append(f"{k}: {np.asarray(v).ravel()}")
            elif isinstance(v, float):
                parts.append(f"{k}: {v:.4f}")
            elif getattr(v, "ndim", None) == 0:
                # 0-d device scalars (a user forwarding unflushed
                # values) format like floats instead of printing a
                # jax.Array repr; note float() on one is a host fetch —
                # fit's own logs are always pre-flushed floats, so the
                # fast path never pays this. Plain ints/bools fall
                # through and keep their native formatting.
                try:
                    parts.append(f"{k}: {float(v):.4f}")
                except (TypeError, ValueError):
                    parts.append(f"{k}: {v}")
            else:
                parts.append(f"{k}: {v}")
        return " - ".join(parts)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            total = f"/{self.steps}" if self.steps else ""
            print(f"step {step}{total} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            print(f"Epoch {epoch + 1} done in {dt:.1f}s - "
                  f"{self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """Reference hapi/callbacks.py:534 — save every `save_freq` epochs."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and self.model is not None and \
                epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir and self.model is not None:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """Reference hapi/callbacks.py:690."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.monitor_op = np.greater
            self.min_delta *= 1
        else:
            self.monitor_op = np.less
            self.min_delta *= -1
        self.best = None
        self.wait = 0
        self.stopped_epoch = 0

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = self.baseline

    def on_eval_end(self, logs=None):
        current = (logs or {}).get(self.monitor)
        if current is None:
            return
        current = float(np.asarray(current).ravel()[0])
        if self.best is None or self.monitor_op(
                current - self.min_delta, self.best):
            self.best = current
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: no {self.monitor} improvement "
                          f"for {self.patience} evals")


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (reference callbacks.py:599)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        from ..optimizer.lr import LRScheduler as Sched
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class History(Callback):
    def on_train_begin(self, logs=None):
        self.history = {}

    def on_epoch_end(self, epoch, logs=None):
        for k, v in (logs or {}).items():
            self.history.setdefault(k, []).append(v)


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=2, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    if not any(isinstance(c, History) for c in cbks):
        cbks.append(History())
    clist = CallbackList(cbks)
    clist.set_model(model)
    clist.set_params({
        "epochs": epochs, "steps": steps, "verbose": verbose,
        "metrics": metrics or [],
    })
    return clist


class VisualDL(Callback):
    """Scalar logging callback (reference hapi/callbacks.py:844 VisualDL).

    The VisualDL service itself is a separate product; this callback
    writes the same per-step/per-epoch scalars as JSONL under
    ``log_dir`` (one record per scalar: {"tag", "step", "value"}), which
    VisualDL/TensorBoard importers and plain pandas read directly.
    """

    def __init__(self, log_dir="./vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._fh = None
        self._step = 0

    def _write(self, tag, value, step):
        import json
        import os
        if self._fh is None:
            os.makedirs(self.log_dir, exist_ok=True)
            self._fh = open(
                os.path.join(self.log_dir, "scalars.jsonl"), "a")
        try:
            v = float(value)
        except (TypeError, ValueError):
            return
        self._fh.write(json.dumps(
            {"tag": tag, "step": int(step), "value": v}) + "\n")

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        for k, v in (logs or {}).items():
            self._write(f"train/{k}", v, self._step)

    def on_epoch_end(self, epoch, logs=None):
        for k, v in (logs or {}).items():
            self._write(f"epoch/{k}", v, epoch)
        if self._fh is not None:
            self._fh.flush()

    def on_eval_end(self, logs=None):
        for k, v in (logs or {}).items():
            self._write(f"eval/{k}", v, self._step)
        if self._fh is not None:
            self._fh.flush()

    def on_train_end(self, logs=None):
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def on_train_abort(self):
        self.on_train_end()   # flush+close is safe teardown either way


class ProfilerCallback(Callback):
    """Profile a window of training steps with the structured span
    profiler (paddle_tpu.profiler.profile) from inside Model.fit.

    Steps ``[start_step, stop_step)`` — counted globally across epochs —
    run under an armed span buffer: every op dispatch, jit-cache miss,
    collective and hapi step lands in the trace. When the window closes
    (or training ends) the callback exports a chrome trace and/or a
    Prometheus text file and optionally prints the span summary table.
    Skipping step 0 (the default ``start_step=1``) keeps the one-off
    trace+compile of the train step out of the steady-state profile;
    pass ``start_step=0`` to capture compilation instead.

    Reference analog: the profiler hooks of hapi's train loop
    (paddle.profiler used as a fit callback) — here rebuilt on span.py.
    """

    def __init__(self, start_step=1, stop_step=4, chrome_trace_path=None,
                 prometheus_path=None, summary=True, verbose=1):
        super().__init__()
        if stop_step is not None and stop_step <= start_step:
            raise ValueError("ProfilerCallback: need stop_step > "
                             "start_step (or stop_step=None)")
        self.start_step = start_step
        self.stop_step = stop_step
        self.chrome_trace_path = chrome_trace_path
        self.prometheus_path = prometheus_path
        self.summary = summary
        self.verbose = verbose
        self._session = None
        self._step_span = None
        self._global_step = 0

    def on_train_begin(self, logs=None):
        self._global_step = 0
        self._session = None
        self._step_span = None

    def on_train_batch_begin(self, step, logs=None):
        from .. import profiler
        g = self._global_step
        if self._session is None and g >= self.start_step and \
                (self.stop_step is None or g < self.stop_step):
            self._session = profiler.profile().__enter__()
        if self._session is not None:
            self._step_span = profiler.record(
                "hapi/step", "hapi", args={"global_step": g}).begin()

    def on_train_batch_end(self, step, logs=None):
        # per-step wall time already lands in the hapi/step_time_ms
        # histogram (Model.train_batch) — no duplicate series here
        if self._step_span is not None:
            self._step_span.end()
            self._step_span = None
        self._global_step += 1
        if self._session is not None and self.stop_step is not None and \
                self._global_step >= self.stop_step:
            self._finish()

    def on_train_end(self, logs=None):
        if self._session is not None:
            self._finish()
        elif self._global_step <= self.start_step and \
                (self.chrome_trace_path or self.prometheus_path):
            import warnings
            warnings.warn(
                f"ProfilerCallback: training ended after "
                f"{self._global_step} step(s), before the profiling "
                f"window at start_step={self.start_step} opened — no "
                f"trace/metrics files were written")

    def on_train_abort(self):
        # still export: the trace of a crashed run is precisely the
        # artifact you want on the way down
        if self._session is not None:
            self._finish()

    def _finish(self):
        from .. import profiler
        if self._step_span is not None:   # step aborted mid-span: close it
            self._step_span.end()
            self._step_span = None
        session, self._session = self._session, None
        session.__exit__(None, None, None)
        if self.chrome_trace_path:
            p = profiler.export_chrome_trace(self.chrome_trace_path)
            if self.verbose:
                print(f"[profiler] chrome trace written to {p} "
                      f"(open in chrome://tracing or Perfetto)")
        if self.prometheus_path:
            profiler.export_prometheus(self.prometheus_path)
            if self.verbose:
                print(f"[profiler] prometheus metrics written to "
                      f"{self.prometheus_path}")
        if self.summary and self.verbose:
            print(profiler.span_summary())


class ReduceLROnPlateau(Callback):
    """Shrink the lr when the monitored metric plateaus (reference
    hapi/callbacks.py ReduceLROnPlateau). Works with either a plain
    float lr or an optimizer.lr scheduler (via set_lr)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = float(factor)
        if self.factor >= 1.0:
            raise ValueError("factor must be < 1.0")
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.monitor_op = np.greater
        else:
            self.monitor_op = np.less
            self.min_delta = -self.min_delta
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0

    def on_train_begin(self, logs=None):
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0

    def _current(self, logs):
        v = (logs or {}).get(self.monitor)
        return None if v is None else float(np.asarray(v).ravel()[0])

    def on_eval_end(self, logs=None):
        current = self._current(logs)
        if current is None:
            return
        in_cooldown = self.cooldown_counter > 0
        if in_cooldown:
            self.cooldown_counter -= 1
            self.wait = 0
        if self.best is None or self.monitor_op(
                current - self.min_delta, self.best):
            self.best = current
            self.wait = 0
            return
        if in_cooldown:
            return            # frozen: stagnation doesn't count yet
        self.wait += 1
        if self.wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is None:
                return
            old = float(opt.get_lr())
            new = max(old * self.factor, self.min_lr)
            if new < old:
                opt.set_lr(new)
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr {old:.3g} -> {new:.3g}")
            self.cooldown_counter = self.cooldown
            self.wait = 0
