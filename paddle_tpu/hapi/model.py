"""``paddle.Model`` — the Keras-like high-level trainer.

Analog of the reference's ``python/paddle/hapi/model.py:915`` (prepare /
fit:1574 / evaluate / predict, Dynamic+Static adapters at :704/:290).

TPU-native design replaces both adapters with ONE path: the whole train step
— forward, loss, backward, grad clip, optimizer update, buffer (BN stat)
update — is a pure function over (params, opt_state, buffers, rng, lr,
batch) compiled once by XLA. The stateful Layer API feeds it through the
``functional_state`` bridge (nn/layer/layers.py). Dropout keys derive from a
per-step folded PRNG key, so masks vary across steps while the trace stays
static. Loss scaling (fp16) runs inside the step; with bf16 (TPU default)
the scaler is inert.
"""
from __future__ import annotations

import os
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import program_registry as _registry
from ..framework import random as _random
from ..framework import trace_probe as _probe
from ..framework.io import load as _load, save as _save
from ..framework.monitor import stat_add, stat_get, stat_observe
from ..framework.tensor import Tensor, no_grad_guard
from ..profiler import memory as _memory
from ..profiler import numerics as _numerics
from ..profiler import span as _prof
from ..io import DataLoader, Dataset
from ..metric import Metric
from ..nn.layer.layers import Layer, functional_state
from . import zero as _zero
from .callbacks import config_callbacks

__all__ = ["Model"]


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _drop_ledger_keys(keys):
    """weakref.finalize target for a Model's HBM-ledger entries — a
    module function so the finalizer holds no reference to the Model."""
    for k in keys:
        _memory.ledger_drop(k)


class _StaticGraphAdapter:
    """Routes Model.fit/evaluate/predict through the static
    Program/Executor when ``paddle.enable_static()`` is active — the
    analog of the reference's StaticGraphAdapter (hapi/model.py:290),
    which builds ProgramDescs instead of running the dygraph engine.

    The network's forward is captured ONCE into a main Program under
    ``program_guard`` (feeds from the Model's InputSpecs), the loss and
    optimizer are appended, and every train_batch is one Executor.run.
    Eval/predict run a ``for_test`` clone of the same capture.

    Train and eval are captured as SEPARATE programs — the train capture
    records train-mode ops (active dropout, batch-stat BN) and the
    test capture records eval-mode ops, mirroring the reference's
    main/test ProgramDesc pair.

    Known gaps vs the dynamic path (both from the replay being pure over
    build-time constants): BatchNorm running stats do not update across
    static training steps (train-mode normalization itself is exact),
    and dropout masks are frozen at capture — active in the train
    program but identical every step. The reference regenerates both via
    in-graph ops."""

    def __init__(self, model: "Model"):
        self.model = model
        self._built = False

    def _spec_name(self, spec, prefix, i):
        return getattr(spec, "name", None) or f"{prefix}_{i}"

    def _capture(self, program, startup=None, with_optimizer=False):
        from .. import static
        m = self.model
        with static.program_guard(program, startup):
            in_vars = [
                static.data(self._spec_name(s, "input", i),
                            list(s.shape), str(s.dtype))
                for i, s in enumerate(m._inputs)]
            label_vars = [
                static.data(self._spec_name(s, "label", i),
                            list(s.shape), str(s.dtype))
                for i, s in enumerate(m._labels)]
            outputs = m.network(*in_vars)
            outs = outputs if isinstance(outputs, (list, tuple)) \
                else [outputs]
            loss = None
            if m._loss is not None and label_vars:
                loss = m._loss(*outs, *label_vars)
                if with_optimizer and m._optimizer is not None:
                    m._optimizer.minimize(loss)
        return loss, outs

    def _build(self):
        from .. import static
        m = self.model
        if not m._inputs:
            raise ValueError(
                "static-graph Model requires inputs=[InputSpec(...)] at "
                "construction (the reference StaticGraphAdapter contract: "
                "feeds must be declared before the program is built)")
        was_training = m.network.training
        main, startup = static.Program(), static.Program()
        try:
            m.network.train()
            self._loss_var, self._out_vars = self._capture(
                main, startup, with_optimizer=True)
            m.network.eval()
            test = static.Program()
            self._test_loss_var, self._test_out_vars = self._capture(test)
        finally:
            m.network.train() if was_training else m.network.eval()
        self._exe = static.Executor()
        self._exe.run(startup)
        self._main, self._test = main, test
        self._in_names = [self._spec_name(s, "input", i)
                          for i, s in enumerate(m._inputs)]
        self._label_names = [self._spec_name(s, "label", i)
                             for i, s in enumerate(m._labels)]
        self._built = True

    def _feed(self, inputs, labels, need_labels):
        if need_labels and self._label_names and not labels:
            raise ValueError(
                f"this batch must include labels for declared feed(s) "
                f"{self._label_names} (the fetched loss depends on them)")
        arrays = _as_arrays(inputs) + (_as_arrays(labels) if labels else [])
        names = self._in_names + (self._label_names if labels else [])
        if len(arrays) != len(names):
            raise ValueError(
                f"batch has {len(arrays)} arrays but the static program "
                f"declares {len(names)} feeds ({names})")
        return dict(zip(names, arrays))

    def train_batch(self, inputs, labels=None):
        if not self._built:
            self._build()
        if self._loss_var is None:
            raise RuntimeError("no loss/labels declared: static-mode "
                               "training needs labels=[InputSpec] + loss")
        fetches = [self._loss_var] + self._out_vars
        res = self._exe.run(self._main,
                            feed=self._feed(inputs, labels, True),
                            fetch_list=fetches)
        loss, outs = res[0], res[1:]
        metrics = self.model._update_metrics(
            outs, _as_arrays(labels) if labels else [])
        loss = float(np.asarray(loss).ravel()[0])
        return (loss, metrics) if metrics else loss

    def eval_batch(self, inputs, labels=None):
        if not self._built:
            self._build()
        with_loss = self._test_loss_var is not None and bool(labels)
        fetches = ([self._test_loss_var] if with_loss else []) \
            + self._test_out_vars
        res = self._exe.run(self._test,
                            feed=self._feed(inputs, labels, with_loss),
                            fetch_list=fetches)
        if with_loss:
            loss, outs = float(np.asarray(res[0]).ravel()[0]), res[1:]
        else:
            loss, outs = 0.0, res
        metrics = self.model._update_metrics(
            outs, _as_arrays(labels) if labels else [])
        return (loss, metrics) if metrics else loss

    def predict_batch(self, inputs):
        if not self._built:
            self._build()
        res = self._exe.run(self._test, feed=self._feed(inputs, None,
                                                        False),
                            fetch_list=self._test_out_vars)
        return [np.asarray(o) for o in res]


def _as_arrays(batch):
    import jax

    def one(b):
        if isinstance(b, Tensor):
            return b._data
        if isinstance(b, jax.Array):
            return b  # already on device: never round-trip through host
        return np.asarray(b)

    if isinstance(batch, (list, tuple)):
        return [one(b) for b in batch]
    return [one(batch)]


class Model:
    # with metrics attached, the async-fit window holds each step's
    # outputs until the flush; this caps how many batches of outputs can
    # be pinned on device when log_freq is large (sync count stays
    # O(steps / min(log_freq, cap)) — still windowed, never per-step)
    _METRIC_WINDOW = 8
    # numerics audit vectors buffered between flushes are tiny ((6 +
    # groups) f32 each) but one per STEP: with log_freq<=0 (epoch-tail
    # flushes only) an unbounded buffer would pin O(steps-per-epoch)
    # device handles — the one invariant the loss window's O(1)
    # overwrite exists to protect. Ring semantics instead: the NEWEST
    # cap's worth survive to the flush (a NaN propagates, so the tail
    # still trips the sentinel even when the origin step was dropped),
    # drops counted in hapi/audit_window_dropped. Forcing a flush would
    # add host syncs vs numerics-off, breaking the identical-sync-budget
    # contract — dropping is the honest bounded choice.
    _AUDIT_WINDOW = 4096

    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step_fn = None
        self._eval_step_fn = None
        self._predict_step_fn = None
        self._params = None       # dict name -> jnp array (device state)
        self._opt_state = None
        self._buffers = None
        self._frozen = None       # stop_gradient param names (static
        #                           split baked into the jitted step)
        self._dirty = False       # functional state newer than network?
        self._step_counter = 0
        self._amp_level = "O0"
        self._amp_dtype = "bfloat16"
        self._static_adapter = None
        self.stop_training = False
        # achieved-FLOP/s accounting for the async fit window: FLOPs of
        # the step programs actually DISPATCHED since the last flush +
        # the window's start stamp (hapi/flops_per_sec, hapi/mfu — see
        # _observe_compute). Summing per dispatch — not steps × the
        # record's latest-compile figure — keeps a partial last batch
        # (its own smaller program) from mis-billing full-batch steps.
        self._flush_flops = 0.0
        self._flush_steps = 0
        self._flush_t0 = None
        # numerics health (profiler/numerics.py): when fit(numerics=)
        # is not 'off', the device-side audit is COMPILED INTO the
        # donated train step (one extra small output + a traced inject
        # scalar, zero extra programs) and its vectors ride the flush
        # window — fetched only behind the window's one blocking loss
        # fetch, so hapi/host_sync is IDENTICAL with numerics on or off
        self._numerics_mode = "off"   # policy applied host-side at flush
        self._audit_enabled = False   # audit baked into the built step?
        self._audit_layout = None     # layer-group schema of the vector
        # [(global step, device vector, layout)] ring — see _AUDIT_WINDOW
        self._audit_window = deque(maxlen=self._AUDIT_WINDOW)
        self._audit_collect = False   # only fit() windows collect
        self._numerics_recorder = None
        self._retrace_mark = 0.0      # dispatch/retrace_cause watermark
        # test hook: scale the loss by +inf when _step_counter hits this
        # value (traced scalar — same compiled program) so the sentinel
        # path is testable without NaN-crafted data
        self._numerics_inject_inf_at = None
        # ZeRO-sharded weight update (hapi/zero.py, fit(zero=1)): the
        # optimizer state lives dp-sharded as flat f32 stripes and the
        # donated step runs reduce-scatter -> shard-local update ->
        # all-gather inside a shard_map over _zero_mesh. _zero_layout
        # is the padding map; _zero_t0 keeps per-param birth steps
        # host-side (the flat analog of the "_t0" slot marker, baked
        # into the step as a constant — a change always rides a
        # frozen-set re-trace). _grad_comm picks the gradient-exchange
        # precision ('fp32' exact | 'int8' EQuARX-style quantized).
        self._zero_stage = 0
        self._grad_comm = "fp32"
        self._zero_mesh = None
        self._zero_layout = None
        self._zero_t0 = {}

    def _static(self):
        """The StaticGraphAdapter when ``paddle.enable_static()`` is on
        (mode is sampled per call, like the reference's _run_backend)."""
        from ..static import in_dynamic_mode
        if in_dynamic_mode():
            return None
        if self._static_adapter is None:
            self._static_adapter = _StaticGraphAdapter(self)
        return self._static_adapter

    # -- preparation --------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metrics must be paddle.metric.Metric, "
                                f"got {type(m)}")
        if amp_configs:
            if isinstance(amp_configs, str):
                self._amp_level = amp_configs
            else:
                self._amp_level = amp_configs.get("level", "O1")
                self._amp_dtype = amp_configs.get("dtype", "bfloat16")
            if self._amp_level == "O2":
                from ..amp import decorate
                decorate(self.network, level="O2", dtype=self._amp_dtype)
        return self

    def _sync_state_from_network(self):
        # snapshot the (name, Tensor) bindings once per sync: the
        # per-step rebind must not pay a recursive module walk
        self._bind_params = list(self.network.named_parameters())
        self._bind_buffers = list(self.network.named_buffers())
        net_params = {n: p._data for n, p in self._bind_params}
        net_buffers = {n: b._data for n, b in self._bind_buffers}
        if self._params is not None:
            # after a donated step the network Tensors hold stale
            # (deleted) handles until _sync_state_to_network runs; for
            # those the functional state IS the current value. A valid
            # network array — user assignment, set_state_dict — still
            # wins, preserving "the network is the API surface".
            def _undeleted(tree, current):
                return {
                    k: current[k]
                    if (k in current and hasattr(v, "is_deleted")
                        and v.is_deleted()) else v
                    for k, v in tree.items()}
            net_params = _undeleted(net_params, self._params)
            net_buffers = _undeleted(net_buffers, self._buffers or {})
        self._params = net_params
        self._buffers = net_buffers
        # frozen set = stop_gradient params. The jitted step bakes it in
        # (static trainable/frozen split), so a change — progressive
        # unfreezing between fits — forces a re-trace and reconciles the
        # optimizer state: surviving moments are kept, newly-trainable
        # params start from zeroed slots, newly-frozen ones are dropped.
        frozen = {name for name, p in self._bind_params
                  if p.stop_gradient}
        # sharded opt state (fit(zero=1)) converts back to the named
        # layout whenever the code below must reconcile it per param —
        # a frozen-set flip, a zero->replicated switch, or a layout no
        # longer matching the trainable tree; otherwise the stripes
        # stay on device untouched (re-fits never round-trip state)
        if self._opt_state is not None and \
                _zero.is_sharded_state(self._opt_state):
            stale = (self._zero_layout is None
                     or not self._zero_layout.compatible_with(
                         {k: v for k, v in self._params.items()
                          if k not in frozen}))
            if not self._zero_stage or frozen != self._frozen or stale:
                self._opt_state = self._zero_gather_named()
        if self._frozen is not None and frozen != self._frozen:
            # invalidate the step; when the rebuilt step re-traces, the
            # hapi/train_step probe site diffs its static frozen_set
            # component and classifies the retrace cause as frozen_set
            # (framework/trace_probe.py) — the recompile-churn analysis
            # pass warns on a flapping set
            self._train_step_fn = None
            if self._optimizer is not None and self._opt_state is not None:
                old = self._opt_state
                trainable = {k: v for k, v in self._params.items()
                             if k not in frozen}
                new_state = self._optimizer.init_state(trainable)
                for name, slots in new_state["slots"].items():
                    old_slots = old["slots"].get(name)
                    if old_slots is None:
                        # newly-trainable param: zeroed moments — record
                        # its birth step so Adam-style bias correction
                        # runs from this param's own t=0 (see "_t0" in
                        # Optimizer.apply_gradients) instead of
                        # mis-scaling against the global step history.
                        # `+ 0` forces a DISTINCT buffer: sharing the
                        # step array across donated slots is a
                        # donate-the-same-buffer-twice XLA error
                        slots["_t0"] = old["step"] + 0
                        continue
                    for sname, arr in old_slots.items():
                        if sname in slots and \
                                arr.shape == slots[sname].shape:
                            slots[sname] = arr
                        elif sname == "_t0":
                            slots[sname] = arr  # keep the birth marker
                new_state["step"] = old["step"]
                self._opt_state = new_state
        self._frozen = frozen
        if self._optimizer is not None and self._opt_state is not None \
                and int(getattr(self._optimizer, "_step_count", 0)) > \
                int(self._opt_state["step"]):
            # eager opt.step() ran since the last mirror: the
            # optimizer's slot store is the newer state — rebuild the
            # functional state from it (the overlay below reads both key
            # namespaces) instead of resuming the stale snapshot and
            # silently discarding the eager progress
            self._opt_state = None
        if self._optimizer is not None and self._opt_state is None:
            self._opt_state = self._optimizer.init_state(
                {k: v for k, v in self._params.items() if k not in frozen})
            # overlay restored slots (optimizer.set_state_dict via
            # Model.load, or prior eager opt.step() training) so existing
            # moments survive the functional re-init. Two key namespaces
            # exist: hapi checkpoints use structural tree names (stable
            # across processes/instances), the eager optimizer keys by
            # Parameter.name (process-global counters) — accept either.
            restored = getattr(self._optimizer, "_slots", {})
            eager_name = {n: p.name
                          for n, p in self.network.named_parameters()}
            any_restored = False
            for name, slots in self._opt_state["slots"].items():
                src = restored.get(name) or \
                    restored.get(eager_name.get(name), {})
                for sname in slots:
                    arr = src.get(sname)
                    if arr is not None and arr.shape == slots[sname].shape:
                        slots[sname] = jnp.asarray(arr, slots[sname].dtype)
                        any_restored = True
                if "_t0" in src:  # birth-step marker rides along
                    slots["_t0"] = jnp.asarray(src["_t0"], jnp.int32) + 0
            # carry the step count only when moments came with it (or the
            # optimizer keeps none, e.g. SGD) — step>0 over zeroed Adam
            # moments would silently mis-scale the bias correction
            step = int(getattr(self._optimizer, "_step_count", 0))
            if step and (any_restored or not self._optimizer._slot_names):
                self._opt_state["step"] = jnp.asarray(step, jnp.int32)
        if self._zero_stage and self._optimizer is not None and \
                self._opt_state is not None and \
                not _zero.is_sharded_state(self._opt_state):
            self._arm_zero()

    def _zero_validate(self):
        """fit(zero=1) compatibility gate — reject configurations the
        flat stripe update cannot express, with the fix in the
        message, instead of training silently-wrong."""
        opt = self._optimizer
        if not getattr(opt, "_flat_rule_supported", True):
            raise ValueError(
                f"fit(zero=1) cannot shard {type(opt).__name__}: its "
                f"update rule has per-parameter semantics a flat stripe "
                f"cannot express (e.g. Lamb's trust ratio); use the "
                f"replicated step (zero=0) or an elementwise optimizer")
        if getattr(opt, "_multi_precision", False):
            raise ValueError(
                "fit(zero=1) does not keep fp32 master-weight slots "
                "(the flat update already runs in f32 over the cast-up "
                "params); disable multi_precision or use zero=0")
        clip = getattr(opt, "_grad_clip", None)
        if clip is not None:
            from ..nn.clip import ClipGradByGlobalNorm, ClipGradByValue
            if not isinstance(clip, (ClipGradByGlobalNorm,
                                     ClipGradByValue)):
                raise ValueError(
                    f"fit(zero=1) supports ClipGradByGlobalNorm (cross-"
                    f"shard psum norm) and ClipGradByValue (elementwise) "
                    f"— {type(clip).__name__} clips per TENSOR, which a "
                    f"flat stripe cannot see; use zero=0")

    def _arm_zero(self):
        """Adopt the ZeRO shard layout: resolve the dp mesh, build the
        padding map over the trainable tree, stripe the NAMED opt state
        onto the mesh, and land params/buffers replicated so the
        compiled step's input shardings are stable from the first
        dispatch. The per-param ``_t0`` birth markers move into a host
        dict (``_zero_t0``) — they only change on frozen-set flips,
        which re-trace anyway, so the step bakes them as a constant."""
        self._zero_validate()
        mesh = _zero.resolve_mesh()
        frozen = frozenset(self._frozen or ())
        trainable = {k: v for k, v in self._params.items()
                     if k not in frozen}
        layout = _zero.FlatLayout.build(
            trainable, int(np.prod(mesh.devices.shape)))
        named = self._opt_state
        self._zero_t0 = {
            name: int(np.asarray(slots["_t0"]))
            for name, slots in named.get("slots", {}).items()
            if "_t0" in slots}
        self._opt_state = _zero.shard_opt_state(
            named, layout, mesh, self._optimizer._slot_names)
        self._zero_mesh, self._zero_layout = mesh, layout
        rep = _zero.replicated_sharding(mesh)
        self._params = {k: jax.device_put(v, rep)
                        for k, v in self._params.items()}
        self._buffers = {k: jax.device_put(v, rep)
                         for k, v in (self._buffers or {}).items()}
        self._rebind_network_state()

    def _zero_gather_named(self):
        """Sharded opt state -> the named {"step", "slots"} layout
        (host gather; fit boundaries only), with the ``_t0`` markers
        re-attached from the host map."""
        named = _zero.gather_opt_state(
            self._opt_state, self._zero_layout,
            self._optimizer._slot_names)
        for name, t0 in self._zero_t0.items():
            if name in named["slots"]:
                named["slots"][name]["_t0"] = jnp.asarray(t0, jnp.int32)
        return named

    def _rebind_network_state(self):
        """Point the network's Tensors at the CURRENT functional state.

        Pure Python reference assignment — no device work, no host sync,
        no module walk (bindings snapshotted in _sync_state_from_network)
        — so the donated train step can run it every dispatch: user code
        reading ``net.some.weight`` between steps sees live post-step
        arrays instead of the donated (deleted) pre-step buffers."""
        if self._params is None:
            return
        binds = getattr(self, "_bind_params", None)
        if binds is None:
            binds = list(self.network.named_parameters())
        for name, p in binds:
            if name in self._params:
                p._data = self._params[name]
        bbinds = getattr(self, "_bind_buffers", None)
        if bbinds is None:
            bbinds = list(self.network.named_buffers())
        for name, b in bbinds:
            if name in self._buffers:
                b._data = self._buffers[name]

    def _sync_state_to_network(self):
        # freshness guard: only mirror when the functional state has
        # advanced since the last sync (_dirty set per dispatch) —
        # unconditional mirroring would roll back eager training done
        # AFTER fit() (p._data and optimizer slots reverting to the
        # fit-era snapshot on a mere model.parameters() call)
        if not self._dirty:
            return
        self._rebind_network_state()
        # mirror the functional opt state back into the optimizer's eager
        # slot store so state_dict()/save() reflect training done through
        # the jitted (donated) step — without this, moments trained in
        # fit() were silently dropped from the .pdopt checkpoint
        if self._optimizer is not None and self._opt_state is not None:
            # a dp-sharded opt state (fit(zero=1)) gathers ON DEMAND
            # here — state_dict()/save() and the eager bridge always
            # see the named layout, so a zero=1 checkpoint is byte-for-
            # byte the replicated format (and restores into either)
            state = self._zero_gather_named() \
                if _zero.is_sharded_state(self._opt_state) \
                else self._opt_state
            self._optimizer._slots = {
                name: dict(slots)
                for name, slots in state["slots"].items()}
            self._optimizer._step_count = int(state["step"])
            # bridge for a later eager opt.step(): Parameter.name ->
            # tree name, so _ensure_slots migrates these entries instead
            # of restarting from zeros (see Optimizer._ensure_slots)
            binds = getattr(self, "_bind_params", None) or \
                list(self.network.named_parameters())
            self._optimizer._slot_aliases = {p.name: n for n, p in binds}
        self._dirty = False

    def _loss_tensors(self, outputs, labels):
        if self._loss is None:
            raise RuntimeError(
                "no loss configured: call model.prepare(optimizer, loss) "
                "before fit/train_batch")
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        loss = self._loss(*outs, *labels)
        return loss

    def _maybe_amp(self):
        from ..amp import auto_cast
        import contextlib
        if self._amp_level in ("O1", "O2"):
            return auto_cast(level=self._amp_level, dtype=self._amp_dtype)
        return contextlib.nullcontext()

    @_prof.record("hapi/build_train_step", "hapi")
    def _build_train_step(self):
        if self._zero_stage:
            return self._build_zero_train_step()
        self._pallas_gate()
        net, opt = self.network, self._optimizer
        clip = getattr(opt, "_grad_clip", None)
        # static split, baked into the trace: frozen (stop_gradient)
        # params are threaded through untouched — no gradient computed,
        # no optimizer slots, output aliases the donated input — which
        # is both the dygraph freezing contract (the old functional step
        # silently trained frozen params) and free under donation
        frozen = frozenset(self._frozen or ())
        # numerics audit (profiler/numerics.py): fused into THIS step's
        # trace when armed — per-step finite bitmask, grad/param/update
        # norms and per-layer-group nonfinite counts as one small f32
        # output next to the loss. 'record'/'warn'/'halt' share the
        # program (policy is host-side at the flush window); only
        # off<->on changes the trace.
        audit_on = self._numerics_mode != "off"
        self._audit_enabled = audit_on
        layout = None
        if audit_on:
            layout = _numerics.AuditLayout.build(
                [k for k in (self._params or {}) if k not in frozen])
        self._audit_layout = layout
        from ..nn.clip import ClipGradByGlobalNorm
        reuse_clip_norm = audit_on and isinstance(clip,
                                                  ClipGradByGlobalNorm)

        # per-INSTANCE site: another Model (even of the same class) must
        # not diff this one's signatures into phantom structure/shape
        # retraces — its first compile is not this model's churn. Held
        # on the Model so rebuilds keep ONE site (and keep counting)
        # even past the trace_probe registry cap.
        probe_site = getattr(self, "_probe_site", None)
        if probe_site is None:
            Model._probe_seq = getattr(Model, "_probe_seq", 0) + 1
            probe_site = self._probe_site = _probe.site(
                f"hapi/train_step[{type(net).__name__}"
                f"#{Model._probe_seq}]")

        def _step(params, opt_state, buffers, key, lr, inject, n_inputs,
                  arrays):
            # body runs only while jax TRACES a new signature, so this
            # classifies every donated-step retrace (shape vs dtype vs
            # frozen-set) into dispatch/retrace_cause at trace time —
            # zero steady-state cost (framework/trace_probe.py)
            probe_site.record(
                _probe.sig_of(list(params.values())
                              + list(buffers.values()) + list(arrays)),
                {"n_inputs": n_inputs, "frozen_set": tuple(sorted(frozen))})
            inputs = arrays[:n_inputs]
            label_arrays = arrays[n_inputs:]
            froz_p = {k: v for k, v in params.items() if k in frozen}
            train_p = {k: v for k, v in params.items() if k not in frozen}

            def loss_of(p):
                with _random.rng_guard(key), self._maybe_amp():
                    with functional_state(net, {**p, **froz_p},
                                          buffers) as st:
                        with no_grad_guard():
                            ins = [Tensor(a, stop_gradient=True)
                                   for a in inputs]
                            outputs = net(*ins)
                            labels = [Tensor(a) for a in label_arrays]
                            loss = self._loss_tensors(outputs, labels)
                    new_buffers = st["updated_buffers"]
                outs = outputs if isinstance(outputs, (list, tuple)) \
                    else [outputs]
                loss_data = loss._data.astype(jnp.float32)
                if audit_on:
                    # traced inject scalar (1.0 in production): the
                    # numerics test hook scales the loss to +inf at a
                    # chosen step through the SAME compiled program
                    loss_data = loss_data * inject
                return loss_data, ([o._data for o in outs], new_buffers)

            (loss_val, (outs, new_buffers)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_p)
            raw_grads = grads
            pre_norm = post_norm = None
            if clip is not None:
                pairs_in = [(train_p[k], g) for k, g in grads.items()]
                if reuse_clip_norm:
                    # the clip already reduces the whole gradient tree
                    # to its global norm — the audit reads that value
                    # instead of paying the reduction twice. min(norm,
                    # clip) IS the exact clipped norm here: the leaves
                    # are plain jnp arrays, so clip_with_norm's eager
                    # Parameter.need_clip exemption never fires and
                    # every grad scales by clip/max(norm, clip)
                    pairs, pre_norm = clip.clip_with_norm(pairs_in)
                    post_norm = jnp.minimum(
                        pre_norm, jnp.float32(clip.clip_norm))
                else:
                    pairs = clip(pairs_in)
                grads = {k: g for (k, (_, g)) in zip(grads.keys(), pairs)}
                if audit_on and post_norm is None:
                    # per-tensor/value clips have no global-norm to
                    # reuse: reduce the CLIPPED grads so the audit's
                    # clip ratio stays honest (reporting 1.0 while a
                    # value clip was biting would hide exactly the
                    # saturation the telemetry exists to expose)
                    post_norm = _numerics.global_grad_norm(grads)
            new_train, new_opt_state = opt.apply_gradients(
                train_p, grads, opt_state, lr)
            new_params = dict(params)
            new_params.update(new_train)
            if audit_on:
                audit = _numerics.build_audit(
                    loss_val, raw_grads, train_p, new_train, layout,
                    grad_norm=pre_norm, clipped_norm=post_norm)
                return (new_params, new_opt_state, new_buffers, loss_val,
                        outs, audit)
            return new_params, new_opt_state, new_buffers, loss_val, outs

        if audit_on:
            def train_step(params, opt_state, buffers, key, lr, inject,
                           n_inputs, *arrays):
                return _step(params, opt_state, buffers, key, lr, inject,
                             n_inputs, arrays)
            static_argnums = (6,)
        else:
            def train_step(params, opt_state, buffers, key, lr, n_inputs,
                           *arrays):
                return _step(params, opt_state, buffers, key, lr, None,
                             n_inputs, arrays)
            static_argnums = (5,)

        # donate params/opt_state/buffers: every output leaf has a
        # same-shape/dtype donated input, so XLA aliases the update
        # in-place instead of allocating a second copy of the whole train
        # state per step — halving train-state HBM residency (the sharded
        # weight-update argument of arXiv 2004.13336, applied to
        # single-chip aliasing). The OLD buffers are deleted the moment
        # the step is dispatched: _dispatch_train_step rebinds
        # self._params/_opt_state/_buffers AND the network's Tensors to
        # the results (reference assignment, no sync), so nothing may —
        # or can accidentally — touch the donated arrays afterwards;
        # a raw pre-step ._data capture raises jax's "Array has been
        # deleted", never silent garbage.
        #
        # The step is an AOT program-registry site (same jit semantics —
        # static n_inputs, donated train state — but the executable is
        # compiled explicitly ONCE per signature): compile wall-ms lands
        # in compile/ms, and the program's XLA cost analysis
        # (FLOPs/bytes) is what _observe_compute turns into
        # hapi/flops_per_sec and hapi/mfu at every flush window. With
        # numerics armed the audit is part of THIS program — never a
        # second compile per signature (bench.py --dry-run asserts the
        # registry compile/count stays flat across a warm re-fit).
        self._train_step_fn = _registry.aot_site(
            probe_site.name, train_step, static_argnums=static_argnums,
            donate_argnums=(0, 1, 2))

    def _build_zero_train_step(self):
        """The ZeRO-sharded twin of ``_build_train_step`` (fit(zero=1),
        hapi/zero.py; arXiv 2004.13336): ONE donated compiled program
        per signature — same argument/static/donation discipline as the
        replicated step — whose body runs inside a ``shard_map`` over
        the dp mesh axis. Per replica: forward+backward on the LOCAL
        batch slice against replicated params, reduce-scatter the flat
        gradient (f32 exact, or the EQuARX-style int8 exchange under
        ``grad_comm='int8'``), shard-local optimizer rule over this
        replica's 1/dp stripe of params and opt state, all-gather the
        updated stripes back into the named tree. Losses/outs leave the
        map as the full-batch mean / the batch-concatenated outputs, so
        everything downstream (flush window, metrics, callbacks) is
        layout-blind. The numerics audit, when armed, is the
        cross-shard variant (build_audit_flat) over the POST-exchange
        dequantized gradient — quantization corruption trips the
        sentinel at the exact step with per-layer-group blame."""
        self._pallas_gate()
        self._zero_validate()
        net, opt = self.network, self._optimizer
        clip = getattr(opt, "_grad_clip", None)
        frozen = frozenset(self._frozen or ())
        mesh, layout = self._zero_mesh, self._zero_layout
        if mesh is None or layout is None:
            raise RuntimeError(
                "zero train step built before the shard layout was "
                "armed — _sync_state_from_network must run first")
        AXIS = _zero.AXIS
        dp, stripe = layout.dp, layout.stripe
        grad_comm = self._grad_comm
        from jax.sharding import PartitionSpec as P
        from ..distributed import collective as _collective
        from ..nn.clip import ClipGradByGlobalNorm, ClipGradByValue
        is_global_clip = isinstance(clip, ClipGradByGlobalNorm)

        audit_on = self._numerics_mode != "off"
        self._audit_enabled = audit_on
        alayout = None
        group_ids = None
        if audit_on:
            alayout = _numerics.AuditLayout.build(
                [k for k in (self._params or {}) if k not in frozen])
            group_ids = layout.group_ids(alayout)
        self._audit_layout = alayout
        # per-param predicates baked as flat constants (they can only
        # change alongside a re-trace): AdamW's decoupled-decay
        # exclusion mask and the _t0 birth-step vector
        decay_mask = None
        if getattr(opt, "_apply_decay_param_fun", None) is not None:
            decay_mask = layout.mask_from(
                [n for n in layout.names if opt._wd_enabled(n)])
        t0_vec = layout.t0_vector(self._zero_t0) if self._zero_t0 \
            else None

        probe_site = getattr(self, "_probe_site", None)
        if probe_site is None:
            Model._probe_seq = getattr(Model, "_probe_seq", 0) + 1
            probe_site = self._probe_site = _probe.site(
                f"hapi/train_step[{type(net).__name__}"
                f"#{Model._probe_seq}]")

        def _stripe_of(full, idx):
            return jax.lax.dynamic_slice(jnp.asarray(full),
                                         (idx * stripe,), (stripe,))

        def _step(params, opt_state, buffers, key, lr, inject, n_inputs,
                  arrays):
            # BODY RUNS INSIDE shard_map: params/buffers/key/lr are
            # replicated per-device views, opt_state["flat"] arrays are
            # this replica's [stripe] slices, arrays are the local
            # batch shard (axis 0 split dp ways)
            idx = jax.lax.axis_index(AXIS)
            rkey = jax.random.fold_in(key, idx)  # per-replica dropout
            inputs = arrays[:n_inputs]
            label_arrays = arrays[n_inputs:]
            froz_p = {k: v for k, v in params.items() if k in frozen}
            train_p = {k: v for k, v in params.items()
                       if k not in frozen}

            def loss_of(p):
                with _random.rng_guard(rkey), self._maybe_amp():
                    with functional_state(net, {**p, **froz_p},
                                          buffers) as st:
                        with no_grad_guard():
                            ins = [Tensor(a, stop_gradient=True)
                                   for a in inputs]
                            outputs = net(*ins)
                            labels = [Tensor(a) for a in label_arrays]
                            loss = self._loss_tensors(outputs, labels)
                    new_buffers = st["updated_buffers"]
                outs = outputs if isinstance(outputs, (list, tuple)) \
                    else [outputs]
                loss_data = loss._data.astype(jnp.float32)
                if audit_on:
                    loss_data = loss_data * inject
                return loss_data, ([o._data for o in outs], new_buffers)

            (loss_val, (outs, new_buffers)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_p)
            # gradient exchange: each replica ends holding the summed
            # 1/dp stripe it owns; /dp turns per-slice-mean grads into
            # the exact full-batch mean (equal slices)
            flat_g = layout.flatten(grads)
            if grad_comm == "int8":
                g_sum = _zero.quantized_reduce_scatter(
                    flat_g, AXIS, dp, stripe, layout.chunk)
            else:
                g_sum = _collective.reduce_scatter_in_axis(flat_g, AXIS)
            g_stripe = g_sum / jnp.float32(dp)
            raw_stripe = g_stripe  # post-exchange, dequantized, pre-clip
            pre_norm = post_norm = None
            if clip is not None:
                if is_global_clip:
                    # the global norm needs the cross-shard psum term —
                    # a local-stripe norm under-clips by ~sqrt(dp)
                    pre_norm = jnp.sqrt(jax.lax.psum(
                        jnp.sum(jnp.square(g_stripe)), AXIS))
                    cn = jnp.float32(clip.clip_norm)
                    g_stripe = g_stripe * (cn / jnp.maximum(pre_norm,
                                                            cn))
                    post_norm = jnp.minimum(pre_norm, cn)
                else:  # ClipGradByValue: elementwise, stripe-local
                    g_stripe = jnp.clip(g_stripe, clip.min, clip.max)
                    if audit_on:
                        post_norm = jnp.sqrt(jax.lax.psum(
                            jnp.sum(jnp.square(g_stripe)), AXIS))
            flat_p = layout.flatten(train_p)
            p_stripe = jax.lax.dynamic_slice(flat_p, (idx * stripe,),
                                             (stripe,))
            step_no = opt_state["step"] + 1
            eff = step_no if t0_vec is None \
                else step_no - _stripe_of(t0_vec, idx)
            mstripe = None if decay_mask is None \
                else _stripe_of(decay_mask, idx)
            new_stripe, new_slots = opt.flat_rule(
                p_stripe, g_stripe, dict(opt_state["flat"]), lr, eff,
                decay_mask=mstripe)
            new_flat = _collective.all_gather_in_axis(
                new_stripe.astype(jnp.float32), AXIS, tiled=True,
                axis=0)
            new_train = layout.unflatten(new_flat, train_p)
            new_params = dict(params)
            new_params.update(new_train)
            new_buffers = _zero.replicate_buffers(new_buffers, AXIS, dp)
            loss_full = jax.lax.pmean(loss_val, AXIS)
            new_state = {"step": step_no, "flat": new_slots}
            if audit_on:
                audit = _numerics.build_audit_flat(
                    loss_full, raw_stripe, p_stripe, new_stripe,
                    _stripe_of(group_ids, idx), alayout, AXIS,
                    grad_norm=pre_norm, clipped_norm=post_norm)
                return (new_params, new_state, new_buffers, loss_full,
                        outs, audit)
            return new_params, new_state, new_buffers, loss_full, outs

        opt_spec = {"step": P(), "flat": P(AXIS)}
        base_in = (P(), opt_spec, P(), P(), P())
        base_out = (P(), opt_spec, P(), P(), P(AXIS))

        # check_vma=False (the shim's name for check_rep): the rep
        # checker cannot statically prove the all-gathered params /
        # pmean'd loss replicated, and the out_specs above ARE the
        # contract (every P() output is produced by an explicit
        # psum/pmean/all_gather)
        if audit_on:
            def train_step(params, opt_state, buffers, key, lr, inject,
                           n_inputs, *arrays):
                probe_site.record(
                    _probe.sig_of(list(params.values())
                                  + list(buffers.values())
                                  + list(arrays)),
                    {"n_inputs": n_inputs,
                     "frozen_set": tuple(sorted(frozen)),
                     "zero": (1, dp, grad_comm)})
                sm = jax.shard_map(
                    lambda p, o, b, k, l, i, arrs: _step(
                        p, o, b, k, l, i, n_inputs, arrs),
                    mesh=mesh, in_specs=base_in + (P(), P(AXIS)),
                    out_specs=base_out + (P(),), check_vma=False)
                return sm(params, opt_state, buffers, key, lr, inject,
                          tuple(arrays))
            static_argnums = (6,)
        else:
            def train_step(params, opt_state, buffers, key, lr,
                           n_inputs, *arrays):
                probe_site.record(
                    _probe.sig_of(list(params.values())
                                  + list(buffers.values())
                                  + list(arrays)),
                    {"n_inputs": n_inputs,
                     "frozen_set": tuple(sorted(frozen)),
                     "zero": (1, dp, grad_comm)})
                sm = jax.shard_map(
                    lambda p, o, b, k, l, arrs: _step(
                        p, o, b, k, l, None, n_inputs, arrs),
                    mesh=mesh, in_specs=base_in + (P(AXIS),),
                    out_specs=base_out, check_vma=False)
                return sm(params, opt_state, buffers, key, lr,
                          tuple(arrays))
            static_argnums = (5,)

        # same donation contract as the replicated step: every donated
        # leaf (params replicated, opt stripes dp-sharded, buffers) has
        # a same-aval same-sharding output to alias — the
        # donation-safety pass stays the standing guard, now through
        # the shard_map eqn
        self._train_step_fn = _registry.aot_site(
            probe_site.name, train_step, static_argnums=static_argnums,
            donate_argnums=(0, 1, 2))

    def _analysis_loss_fn(self, ins, lbs):
        """Loss-of-trainable-params closure mirroring _build_train_step's
        ``loss_of`` — the analysis layer (paddle_tpu/analysis) traces
        ``jax.grad`` of this for the dead/frozen-grad pass. Kept here so
        the functional_state/amp/rng plumbing has ONE owner."""
        import jax
        net = self.network
        frozen = frozenset(self._frozen or ())
        params, buffers = self._params, self._buffers
        froz_p = {k: v for k, v in params.items() if k in frozen}
        train_p = {k: v for k, v in params.items() if k not in frozen}
        key = jax.random.key(0)

        def loss_fn(p):
            with _random.rng_guard(key), self._maybe_amp():
                with functional_state(net, {**p, **froz_p}, buffers):
                    with no_grad_guard():
                        tins = [Tensor(a, stop_gradient=True)
                                for a in ins]
                        outputs = net(*tins)
                        labels = [Tensor(a) for a in lbs]
                        loss = self._loss_tensors(outputs, labels)
            return loss._data.astype(jnp.float32)

        return loss_fn, train_p

    def _run_analysis(self, inputs, labels, mode):
        """fit()'s pre-flight: lint the built train step on the first
        batch. 'warn' logs the findings table; 'error' additionally
        raises AnalysisError on error-severity findings. Analyzer
        crashes (not findings) never kill training.

        Also reports the step's donation-aware ``static_peak_bytes``
        (the static-memory pass figure, ISSUE 18) — one log line before
        any compile, plus the ``analysis/train_step_peak_bytes`` gauge —
        so an over-HBM train step is visible from the plan, not from an
        XLA OOM minutes later. Donation misses surface through the same
        findings table (donation-miss pass warnings)."""
        from .. import analysis
        try:
            report = analysis.analyze_model(self, inputs, labels)
        except Exception as e:  # pragma: no cover - analyzer robustness
            import warnings
            warnings.warn(f"static analysis pre-flight failed "
                          f"({type(e).__name__}: {e}); continuing fit",
                          RuntimeWarning)
            return None
        for f in report.findings:
            if f.pass_id == "static-memory" and f.data:
                peak = f.data.get("static_peak_bytes")
                if peak is not None:
                    import sys
                    from ..framework.monitor import stat_observe
                    stat_observe("analysis/train_step_peak_bytes", peak)
                    print(f"[analysis] train step static peak: "
                          f"{peak:,} B ({peak / (1 << 20):.1f} MiB, "
                          f"donation-aware; pre-compile estimate)",
                          file=sys.stderr)
                break
        return analysis.apply_mode(report, mode, "the train step")

    def _build_eval_step(self):
        net = self.network

        def eval_step(params, buffers, key, n_inputs, *arrays):
            inputs = arrays[:n_inputs]
            label_arrays = arrays[n_inputs:]
            with _random.rng_guard(key), self._maybe_amp():
                with functional_state(net, params, buffers):
                    with no_grad_guard():
                        ins = [Tensor(a, stop_gradient=True)
                               for a in inputs]
                        outputs = net(*ins)
                        outs = outputs if isinstance(outputs, (list, tuple))\
                            else [outputs]
                        if self._loss is not None and label_arrays:
                            labels = [Tensor(a) for a in label_arrays]
                            loss = self._loss_tensors(outputs, labels)._data
                        else:
                            loss = jnp.zeros((), jnp.float32)
            return loss, [o._data for o in outs]

        # no donation here: eval/predict REUSE params and buffers across
        # batches (the step returns neither), so donating them would
        # delete live state after the first batch. Registry site like
        # the train step (static n_inputs at position 3).
        self._eval_step_fn = _registry.aot_site(
            "hapi/eval_step", eval_step, static_argnums=(3,))

    # -- single-batch APIs (reference train_batch/eval_batch/predict_batch) -
    def _pallas_gate(self):
        # same smoke gate as ParallelEngine._build: a Pallas kernel that
        # cannot lower on this chip must degrade to lax, not crash fit()
        from ..ops import pallas_smoke
        pallas_smoke.ensure()

    def _dispatch_train_step(self, ins, lbs):
        """Dispatch ONE donated jitted step and rebind the train state.

        Returns (loss, outs) as device values without any host sync —
        the donation contract lives here: the previous
        params/opt_state/buffers are consumed by the call, so they are
        rebound to the step's results in the same statement and the old
        handles are never touched again."""
        self._step_counter += 1
        if self._flush_t0 is None:
            self._flush_t0 = time.perf_counter()
        self._flush_steps += 1
        key = jax.random.fold_in(jax.random.key(0), self._step_counter)
        lr = jnp.asarray(self._optimizer.get_lr(), jnp.float32)
        if self._audit_enabled:
            inj = self._numerics_inject_inf_at
            inject = np.float32(np.inf) if (
                inj is not None and self._step_counter == inj) \
                else np.float32(1.0)
            (self._params, self._opt_state, self._buffers, loss, outs,
             audit) = self._train_step_fn(
                self._params, self._opt_state, self._buffers, key, lr,
                inject, len(ins), *ins, *lbs)
            if self._audit_collect:
                # tiny device vector per step ((6 + groups) f32), held
                # until the window flush fetches it behind the loss;
                # the layout rides along so a mid-epoch step rebuild
                # (frozen-set flip) can never decode old vectors
                # against a new group schema
                w = self._audit_window
                if w.maxlen is not None and len(w) == w.maxlen:
                    stat_add("hapi/audit_window_dropped")
                w.append((self._step_counter, audit, self._audit_layout))
        else:
            (self._params, self._opt_state, self._buffers, loss,
             outs) = self._train_step_fn(
                self._params, self._opt_state, self._buffers, key, lr,
                len(ins), *ins, *lbs)
        self._flush_flops += getattr(self._train_step_fn,
                                     "last_dispatch_flops", None) or 0.0
        self._dirty = True
        # reference-only rebind (no sync): the network must never be
        # left pointing at the donated pre-step buffers
        self._rebind_network_state()
        # sampled collective device timing (ISSUE 13): the zero step's
        # exchange is fused inside the donated program, so its cost is
        # priced by an isolated same-shape probe — first step always
        # (the dry-run/bench canaries see it), then at the
        # FLAGS_collective_timing_every stride. Host-side, outside the
        # step: the probe blocks on ITS OWN tiny program, never on the
        # in-flight train step.
        if self._zero_stage and self._zero_mesh is not None \
                and self._zero_layout is not None:
            from ..distributed import collective as _collective
            # stride keyed per comm mode: flipping fp32 -> int8 changes
            # the probed wire shape, and its FIRST step must sample too
            if _collective.timing_sampled(
                    f"zero_step_probe_{self._grad_comm}"):
                try:
                    _zero.time_step_collectives(
                        self._zero_mesh, self._zero_layout,
                        self._grad_comm)
                except Exception:                        # noqa: BLE001
                    pass    # a failed probe must never fail a train step
        return loss, outs

    def _ensure_train_built(self):
        if self._train_step_fn is None or self._params is None:
            self.network.train()
            self._sync_state_from_network()
        elif self._frozen is not None and \
                getattr(self, "_bind_params", None):
            # cheap staleness probe (attr reads over the cached binds, no
            # module walk): stop_gradient flips between raw train_batch
            # calls must re-trace + reconcile optimizer slots exactly as
            # they do at fit() start — otherwise the frozen split baked
            # into the jitted step silently keeps training frozen params
            frozen_now = {n for n, p in self._bind_params
                          if p.stop_gradient}
            if frozen_now != self._frozen:
                self._sync_state_from_network()
        if self._train_step_fn is None:  # fresh build or forced re-trace
            self._build_train_step()

    def train_batch(self, inputs, labels=None, update=True,
                    return_numpy=True):
        """One optimizer step.  ``return_numpy=False`` returns the loss as
        a device scalar WITHOUT blocking on the chip — jax's async dispatch
        then pipelines successive steps (the reference's dygraph step is
        synchronous by construction; on TPU a per-step host sync costs
        tens of ms through the runtime, so the non-blocking form is the
        fast path for tight loops)."""
        adapter = self._static()
        if adapter is not None:
            return adapter.train_batch(inputs, labels)
        loss, outs, lbs = self._timed_dispatch(inputs, labels)
        metrics = self._update_metrics(outs, lbs)
        if return_numpy:
            loss = float(loss)
        return (loss, metrics) if metrics else loss

    def _timed_dispatch(self, inputs, labels):
        """Build-if-needed + span + one async dispatch: the shared body
        of train_batch and fit's inner loop. Returns device (loss, outs)
        plus the coerced label arrays (for metric updates).

        hapi/step_time_ms is HOST wall time of the step call: jax
        dispatches asynchronously, so this measures dispatch+tracing,
        not device compute — the span/histogram pair still localises
        stalls (compiles, H2D, syncs)."""
        t0 = time.perf_counter()
        with _prof.record("hapi/train_batch", "hapi"):
            self._ensure_train_built()
            ins = _as_arrays(inputs)
            lbs = _as_arrays(labels) if labels is not None else []
            if self._zero_stage and self._zero_layout is not None:
                self._zero_batch_guard(ins + lbs)
            loss, outs = self._dispatch_train_step(ins, lbs)
        stat_observe("hapi/step_time_ms", (time.perf_counter() - t0) * 1e3)
        return loss, outs, lbs

    def eval_batch(self, inputs, labels=None):
        adapter = self._static()
        if adapter is not None:
            return adapter.eval_batch(inputs, labels)
        with _prof.record("hapi/eval_batch", "hapi"):
            if self._eval_step_fn is None:
                self._build_eval_step()
            if self._params is None:
                self._sync_state_from_network()
            ins = _as_arrays(inputs)
            lbs = _as_arrays(labels) if labels is not None else []
            key = jax.random.key(0)
            loss, outs = self._eval_step_fn(
                self._params, self._buffers, key, len(ins), *ins, *lbs)
            metrics = self._update_metrics(outs, lbs)
            loss = float(loss)
        return (loss, metrics) if metrics else loss

    def predict_batch(self, inputs):
        adapter = self._static()
        if adapter is not None:
            return adapter.predict_batch(inputs)
        if self._eval_step_fn is None:
            self._build_eval_step()
        if self._params is None:
            self._sync_state_from_network()
        ins = _as_arrays(inputs)
        _, outs = self._eval_step_fn(
            self._params, self._buffers, jax.random.key(0), len(ins), *ins)
        return [np.asarray(o) for o in outs]

    def _update_metrics(self, outs, labels):
        results = []
        for m in self._metrics:
            # wrap labels directly — np.asarray on a device-resident label
            # batch is a blocking D2H sync per step
            correct = m.compute(*[Tensor(o) for o in outs],
                                *[Tensor(l) for l in labels])
            r = m.update(*(correct if isinstance(correct, tuple)
                           else (correct,)))
            results.append(r)
        return results

    # -- fit/evaluate/predict ------------------------------------------------
    def _as_loader(self, data, batch_size, shuffle, num_workers, drop_last):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)

    def _zero_batch_guard(self, arrays):
        """The helpful face of the zero=1 batch contract: every array's
        axis 0 must split evenly across dp. Raised from BOTH entries —
        the dispatch path and the prefetch producer (which would
        otherwise surface jax's opaque 'global size of its dimension 0
        should be divisible' from the dp-sharded device_put on a tail
        batch)."""
        dp = self._zero_layout.dp if self._zero_layout is not None \
            else None
        if not dp:
            return
        for a in arrays:
            shape = getattr(a, "shape", ())
            if shape and shape[0] % dp:
                raise ValueError(
                    f"fit(zero=1) splits the batch across dp={dp} "
                    f"replicas but got axis-0 size {shape[0]}; use a "
                    f"batch size divisible by dp (drop_last=True for "
                    f"the tail)")

    def _maybe_prefetch(self, loader, prefetch, buffer_size=2,
                        train=False):
        """Wrap ``loader`` in io.device_prefetch unless switched off by
        the ``prefetch`` argument (None defers to FLAGS_hapi_prefetch) or
        static mode. Sharding-aware: set ``model._prefetch_sharding`` to
        a jax.sharding.Sharding to land batches pre-sharded. With the
        ZeRO-sharded step armed (fit(zero=1)) and no explicit override,
        TRAIN batches derive the step's own dp batch sharding — they
        land pre-split across the mesh instead of replicated-then-
        resharded (a gather the sharded train state never needs)."""
        from ..framework.flags import flag_value
        if loader is None or self._static() is not None:
            return loader
        if prefetch is None:
            prefetch = bool(flag_value("FLAGS_hapi_prefetch"))
        if not prefetch:
            return loader
        from ..io import device_prefetch
        sharding = getattr(self, "_prefetch_sharding", None)
        if sharding is None and train and self._zero_stage \
                and self._zero_mesh is not None:
            sharding = _zero.dp_sharding(self._zero_mesh)

            def _guarded(it):
                # validate BEFORE the dp-sharded device_put: a
                # non-divisible tail batch must fail with the
                # drop_last=True hint, not jax's sharding error from
                # the prefetch producer thread
                for batch in it:
                    arrays = batch if isinstance(batch, (list, tuple)) \
                        else [batch]
                    self._zero_batch_guard(
                        [getattr(a, "_data", a) for a in arrays])
                    yield batch

            loader = _guarded(loader)
        return device_prefetch(loader, sharding=sharding,
                               buffer_size=buffer_size)

    def _flush_window(self, window):
        """ONE host sync for a window of buffered device step results:
        fetch the last loss (its value bounds every queued step, so this
        is the only pipeline stall), then run the windowed metric updates
        — their D2H copies read already-computed arrays. Counted in
        ``hapi/host_sync`` so the sync budget of fit() is asserted by
        tests and bench.py --dry-run, not assumed."""
        if not window:
            return {}
        t0 = time.perf_counter()
        with _prof.record("hapi/host_sync", "hapi",
                          args={"steps": len(window)}):
            loss = float(np.asarray(window[-1][0]).ravel()[0])
            metrics = []
            for _, outs, lbs in window:
                if outs is not None:
                    metrics = self._update_metrics(outs, lbs)
        window.clear()
        stat_add("hapi/host_sync")
        stat_observe("hapi/host_sync_ms",
                     (time.perf_counter() - t0) * 1e3)
        logs = self._pack_logs((loss, metrics) if metrics else loss)
        logs.update(self._observe_compute())
        # HBM watermark at the step-boundary surface (the flush already
        # blocks on the host sync; one PjRt stats query rides along)
        _memory.sample("hapi/flush", steps=self._step_counter)
        # numerics: decode the window's audit vectors (already-computed
        # device arrays behind the loss fetch above — no extra sync, the
        # hapi/host_sync counter is untouched), feed the telemetry
        # histograms + the training flight recorder, and apply the
        # policy — 'halt' raises NumericsError here, AFTER its anomaly
        # postmortem dump, and propagates through fit's on_train_abort
        # teardown like any other training failure
        logs.update(self._flush_numerics())
        return logs

    def _flush_numerics(self):
        """Drain the window's audit vectors into the numerics recorder
        (profiler/numerics.py). Returns the flush-log update
        (``grad_norm`` + ``loss_scale``); raises only
        :class:`~paddle_tpu.profiler.numerics.NumericsError` (halt
        mode) — recorder bugs degrade to a warning, never kill a run
        the audit exists to protect."""
        if not self._audit_window:
            return {}
        entries = list(self._audit_window)
        self._audit_window.clear()
        rec = self._numerics_recorder
        if rec is None:
            return {}
        retrace_now = stat_get("dispatch/retrace_cause")
        delta = retrace_now - self._retrace_mark
        self._retrace_mark = retrace_now
        from ..amp import active_scaler
        # the process's newest ENABLED scaler: hapi's bf16-native step
        # drives no GradScaler itself, so the recorded state is ambient
        # context (which custom-AMP-loop scaler was live during this
        # fit), not a claim that fit consumed it
        scaler = active_scaler()
        kwargs = dict(
            mode=self._numerics_mode,
            lr=float(self._optimizer.get_lr()),
            scaler=scaler.state() if scaler is not None else None,
            retrace_delta=int(delta),
            ledger_bytes=_memory.ledger_total(),
            context={"site": getattr(getattr(self, "_probe_site", None),
                                     "name", None)})
        try:
            # decode each vector against the layout IT was produced
            # under: a mid-window step rebuild (frozen-set flip via the
            # staleness probe) changes the group schema, and zipping an
            # old vector against the new groups would silently blame
            # the wrong layers. Consecutive same-layout runs share one
            # record_window call.
            logs = {}
            i, n = 0, len(entries)
            while i < n:
                layout = entries[i][2]
                j = i
                while j < n and entries[j][2] is layout:
                    j += 1
                if layout is not None:
                    logs = rec.record_window(
                        [(step, np.asarray(a))
                         for step, a, _ in entries[i:j]],
                        layout, **kwargs)
                i = j
            return logs
        except _numerics.NumericsError:
            raise
        except Exception as e:  # pragma: no cover - recorder robustness
            import warnings
            warnings.warn(f"numerics flush failed "
                          f"({type(e).__name__}: {e}); continuing fit",
                          RuntimeWarning)
            return {}

    def _observe_compute(self):
        """Achieved FLOP/s (and MFU against the device peak) for the
        steps dispatched since the last flush, from the train step's
        program-registry cost analysis: ``hapi/flops_per_sec`` always
        when the backend reports FLOPs, ``hapi/mfu`` (plus an ``mfu``
        entry in the flush logs, which the ProgBar prints) only when a
        peak is known — the per-device table in
        ``framework/program_registry.py``, overridable with
        ``PADDLE_TPU_PEAK_FLOPS``; CPU has no honest peak. The FIRST
        window includes trace+compile wall time, exactly like
        ``hapi/step_time_ms``."""
        now = time.perf_counter()
        flops, self._flush_flops = self._flush_flops, 0.0
        steps, self._flush_steps = self._flush_steps, 0
        # re-arm lazily (next dispatch stamps the window start), NOT at
        # `now`: eval/checkpoint wall time between the epoch-end flush
        # and the next epoch's first batch must not deflate the next
        # window's FLOP/s into a fake per-epoch MFU dip
        t0, self._flush_t0 = self._flush_t0, None
        out = {}
        if not flops or not steps or t0 is None:
            return out
        wall = now - t0
        if wall <= 0:
            return out
        achieved = flops / wall
        stat_observe("hapi/flops_per_sec", achieved)
        peak = _registry.peak_flops()
        if peak:
            out["mfu"] = achieved / peak
            stat_observe("hapi/mfu", out["mfu"])
        return out

    def _update_memory_ledger(self):
        """Register the train state's bytes in the HBM ledger
        (profiler/memory.py) — the 'what WE think is live' side of the
        ledger-vs-device crosscheck. Host arithmetic over avals only.

        Keys are per-INSTANCE (the train step's probe-site name as the
        prefix) so two Models in one process never alias each other's
        entries, and a weakref finalizer drops them when the Model is
        collected — a discarded model must not haunt the crosscheck or
        an OOM postmortem with train state that is no longer live."""
        import weakref

        def tree_bytes(tree):
            return sum(int(getattr(v, "nbytes", 0))
                       for v in jax.tree_util.tree_leaves(tree or {}))
        base = getattr(self, "_ledger_base", None)
        if base is None:
            site = getattr(self, "_probe_site", None)
            name = site.name if site is not None else \
                f"hapi/train_step[{type(self.network).__name__}" \
                f"@{id(self):x}]"
            base = self._ledger_base = name.replace(
                "hapi/train_step", "hapi/state", 1)
            keys = [f"{base}/params", f"{base}/opt_state",
                    f"{base}/buffers"]
            weakref.finalize(self, _drop_ledger_keys, keys)
        _memory.ledger_set(f"{base}/params", tree_bytes(self._params))
        # the ledger records PER-REPLICA residency (what one chip
        # holds): a dp-sharded opt state (fit(zero=1)) bills its flat
        # stripes at 1/dp of the logical bytes — the HBM win the ZeRO
        # rewrite exists for, proven by the same ledger that would
        # catch it regressing
        opt_bytes = tree_bytes(self._opt_state)
        if self._opt_state is not None and \
                _zero.is_sharded_state(self._opt_state) and \
                self._zero_layout is not None:
            flat_bytes = tree_bytes(self._opt_state.get("flat"))
            opt_bytes = (opt_bytes - flat_bytes
                         + flat_bytes // self._zero_layout.dp)
        _memory.ledger_set(f"{base}/opt_state", opt_bytes)
        _memory.ledger_set(f"{base}/buffers", tree_bytes(self._buffers))

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            prefetch=None, prefetch_buffer_size=2, analyze=None,
            numerics=None, zero=None, grad_comm=None):
        """Train over ``train_data``, asynchronously on the dygraph path:
        steps are dispatched without blocking (donated jitted step), the
        next batch's H2D transfer rides under compute via
        ``io.device_prefetch`` (``prefetch=None`` defers to
        ``FLAGS_hapi_prefetch``; pass False for iterables that must not
        be read ahead), and loss/metrics stay device values flushed to
        the host only every ``log_freq`` steps and at epoch end — O(steps
        / log_freq) host syncs per epoch (the ``hapi/host_sync`` counter)
        instead of one stall per batch (with metrics attached the window
        additionally caps at ``_METRIC_WINDOW`` steps so pinned outputs
        stay bounded). Between flushes,
        ``on_train_batch_end`` receives the last flushed logs, so
        per-step scalar consumers (e.g. VisualDL) see values at
        ``log_freq`` granularity on this path; the static-graph adapter
        keeps per-step logs (its executor is host-synchronous anyway).

        ``analyze`` runs the jaxpr linter (paddle_tpu/analysis) over the
        built train step on the first batch: ``'warn'`` logs findings,
        ``'error'`` raises AnalysisError on error-severity ones,
        ``'off'`` skips. ``None`` defers to ``FLAGS_static_analysis``
        (env-seeded, default off). Tracing only — nothing executes.

        ``numerics`` arms the training numerics health layer
        (profiler/numerics.py): a device-side audit (finite bitmask,
        grad/param/update norms, per-layer-group nonfinite counts)
        FUSED into the donated train step and fetched only at the flush
        windows — zero extra host syncs (``hapi/host_sync`` is
        identical on/off) and zero extra compiled programs. ``'record'``
        feeds the ``hapi/grad_norm``/``update_ratio``/
        ``grad_clip_ratio`` histograms and the bounded training flight
        recorder; ``'warn'`` additionally dumps an anomaly postmortem
        JSON and warns on nonfinite steps or robust-z loss spikes;
        ``'halt'`` raises :class:`NumericsError` on a nonfinite step
        AFTER the postmortem lands (``on_train_abort`` teardown runs).
        ``None`` defers to ``FLAGS_numerics`` /
        ``FLAGS_check_nan_inf`` (the reference flag's abort-on-NaN
        semantics map to ``'halt'``), default ``'off'``.

        ``zero=1`` arms the ZeRO-sharded weight update (hapi/zero.py,
        arXiv 2004.13336): the donated train step runs inside a
        ``shard_map`` over the dp mesh axis — reduce-scatter grads,
        shard-local optimizer over a 1/dp stripe of the (flat,
        dp-sharded) optimizer state, all-gather updated params — one
        compiled donated program, bit-identical training math, and
        per-replica opt-state HBM cut ~dp-fold (the PR-7 ledger bills
        the stripes). Optimizer state lives SHARDED between steps;
        ``state_dict``/``save``/the eager bridge gather on demand and
        ``load`` re-shards, so checkpoints are mode-portable. ``None``
        defers to ``FLAGS_zero_stage`` (default 0). Batch axis 0 must
        divide by dp, and the loss must be an equal-weight MEAN over
        the batch (every built-in loss's default reduction): the
        gradient exchange averages per-slice gradients, the standard
        data-parallel contract (``paddle.DataParallel``/DDP) — a
        ``reduction='sum'`` loss, or one whose per-sample weights
        concentrate unevenly in a slice (``ignore_index``), follows
        the dp-averaged semantics, not the single-process ones.
        ``grad_comm='int8'`` additionally runs the
        gradient exchange quantized (EQuARX-style per-chunk max-abs
        scales computed in-step, ~4x fewer wire bytes — the
        ``collective_bytes/*`` counters prove it), with the numerics
        audit reading the DEQUANTIZED gradient so corruption is blamed
        at the exact step; default ``'fp32'`` (exact), ``None`` defers
        to ``FLAGS_grad_comm``."""
        analyze_explicit = analyze is not None
        if analyze is None:
            # flag-seeded: lenient normalization (a bad env value means
            # un-linted, not a crash blaming an argument never passed)
            from .. import analysis
            analyze = analysis.flag_mode()
        elif analyze not in ("off", "warn", "error"):
            raise ValueError(
                f"analyze must be 'warn', 'error' or 'off', got "
                f"{analyze!r}")
        numerics_explicit = numerics is not None
        if numerics is None:
            numerics = _numerics.flag_mode()
        elif numerics not in _numerics.MODES:
            raise ValueError(
                f"numerics must be one of {_numerics.MODES}, got "
                f"{numerics!r}")
        zero_explicit = zero is not None
        if zero is None:
            # env-seeded, leniently normalized like the sibling flags:
            # a bad FLAGS_zero_stage value means replicated, not a
            # crash blaming an argument that was never passed
            from ..framework.flags import flag_value
            try:
                zero = 1 if int(flag_value("FLAGS_zero_stage") or 0) \
                    >= 1 else 0
            except (TypeError, ValueError):
                zero = 0
        elif zero in (0, 1, False, True):
            zero = int(zero)
        else:
            raise ValueError(
                f"zero must be 0 or 1 (ZeRO stage-1 optimizer-state "
                f"sharding), got {zero!r}")
        if grad_comm is None:
            from ..framework.flags import flag_value
            gc = str(flag_value("FLAGS_grad_comm") or "fp32").strip() \
                .lower()
            grad_comm = gc if gc in ("fp32", "int8") else "fp32"
        elif grad_comm not in ("fp32", "int8"):
            raise ValueError(
                f"grad_comm must be 'fp32' or 'int8', got {grad_comm!r}")
        loader = self._as_loader(train_data, batch_size, shuffle,
                                 num_workers, drop_last)
        eval_loader = self._as_loader(eval_data, batch_size, False,
                                      num_workers, False)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps,
            log_freq=log_freq, verbose=verbose, save_freq=save_freq,
            save_dir=save_dir, metrics=self._metric_names())
        self.stop_training = False
        self.network.train()
        async_path = self._static() is None
        if analyze != "off" and not async_path:
            # the jaxpr linter hooks the DYNAMIC donated train step; on
            # the static-graph adapter the analog is the Executor.run
            # pre-flight. Warn only for an EXPLICIT analyze= request
            # (error mode could never fire) — a flag-seeded mode already
            # covers static programs through that pre-flight, so there
            # is nothing to advise
            if analyze_explicit:
                import warnings
                warnings.warn(
                    "fit(analyze=...) applies to the dynamic-graph path; "
                    "in static mode the FLAGS_static_analysis pre-flight "
                    "at Executor.run lints the captured Program",
                    UserWarning)
            analyze = "off"
        if numerics != "off" and not async_path:
            # the audit is fused into the DYNAMIC donated train step;
            # the static-graph Executor is host-synchronous per batch —
            # its loss is already on the host every step
            if numerics_explicit:
                import warnings
                warnings.warn(
                    "fit(numerics=...) applies to the dynamic-graph "
                    "path; the static-graph executor fetches the loss "
                    "every batch already", UserWarning)
            numerics = "off"
        if zero and not async_path:
            # the sharded weight update lives in the DYNAMIC donated
            # step; the static-graph executor replays a captured
            # Program per batch
            if zero_explicit:
                import warnings
                warnings.warn(
                    "fit(zero=...) applies to the dynamic-graph path; "
                    "the static-graph executor runs the captured "
                    "Program unsharded", UserWarning)
            zero = 0
        if async_path:
            # off<->on changes the step's trace (the audit output and
            # inject scalar are part of the program); record/warn/halt
            # share it — the policy is host-side, switching is free
            if (numerics != "off") != self._audit_enabled \
                    and self._train_step_fn is not None:
                self._train_step_fn = None
            # a zero-stage or grad-comm flip is a different program:
            # invalidate the step (the opt-state layout transition —
            # shard or gather — happens in _sync_state_from_network)
            if (zero != self._zero_stage
                    or (zero and grad_comm != self._grad_comm)) \
                    and self._train_step_fn is not None:
                self._train_step_fn = None
            self._zero_stage, self._grad_comm = zero, grad_comm
            self._numerics_mode = numerics
            self._sync_state_from_network()
            if self._train_step_fn is None:
                self._build_train_step()
            self._update_memory_ledger()
            if numerics != "off":
                if self._numerics_recorder is None:
                    self._numerics_recorder = _numerics.NumericsRecorder()
                # ring continuity is kept across fits; the loss-spike
                # baseline is not (a new task's healthy starting loss
                # must not z-score against the last run's converged one)
                self._numerics_recorder.new_run()
                self._audit_window = deque(maxlen=self._AUDIT_WINDOW)
                self._audit_collect = True
                self._retrace_mark = stat_get("dispatch/retrace_cause")
            else:
                # an ABORTED numerics fit can leave un-flushed vectors
                # behind (collect stops in the finally, the window does
                # not drain) — a later numerics-off fit must not decode
                # the previous run's leftovers into the recorder
                self._audit_window.clear()
        self._flush_flops, self._flush_steps, self._flush_t0 = 0.0, 0, None
        cbks.on_train_begin()
        try:
            for epoch in range(epochs):
                if self.stop_training:
                    break
                cbks.on_epoch_begin(epoch)
                for m in self._metrics:
                    m.reset()
                logs = {}
                window = []
                data_iter = self._maybe_prefetch(loader, prefetch,
                                                 prefetch_buffer_size,
                                                 train=True)
                for step, batch in enumerate(data_iter):
                    cbks.on_train_batch_begin(step)
                    inputs, labels = self._split_batch(batch)
                    if (analyze != "off" and async_path
                            and epoch == 0 and step == 0):
                        self._analysis_report = self._run_analysis(
                            inputs, labels, analyze)
                    if not async_path:
                        result = self.train_batch(inputs, labels)
                        logs = self._pack_logs(result)
                    else:
                        loss, outs, lbs = self._timed_dispatch(inputs,
                                                               labels)
                        # without metrics the outputs are dead weight —
                        # drop the refs so XLA frees them immediately
                        # (GPT-size logits held over a window would
                        # otherwise pin log_freq batches of HBM); WITH
                        # metrics the window itself must pin outputs, so
                        # its length is capped: at most _METRIC_WINDOW
                        # batches of outputs live on device even when
                        # log_freq is large
                        entry = (loss, outs if self._metrics else None,
                                 lbs if self._metrics else None)
                        if self._metrics or not window:
                            window.append(entry)
                        else:
                            # loss-only window: _flush_window reads just
                            # the last loss, so keep O(1) device buffers
                            # alive however large log_freq is
                            window[0] = entry
                        # log_freq <= 0 means "epoch-end flushes only"
                        # (pre-async fit accepted 0 as 'never log')
                        if (log_freq > 0 and step % log_freq == 0) or (
                                self._metrics and
                                len(window) >= self._METRIC_WINDOW):
                            logs = self._flush_window(window)
                    cbks.on_train_batch_end(step, logs)
                if window:  # tail of the epoch since the last flush
                    logs = self._flush_window(window)
                cbks.on_epoch_end(epoch, logs)
                if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                    self.evaluate(eval_loader, batch_size=batch_size,
                                  verbose=verbose, callbacks=cbks,
                                  prefetch=prefetch, _inside_fit=True)
            cbks.on_train_end()
        except BaseException as e:
            # an out-of-HBM death leaves the memory picture behind: the
            # tracker's timeline, the ledger (params/opt_state/buffers +
            # KV pools), and the largest live arrays, as JSON next to
            # the serving flight recorder's dumps. Best-effort — the
            # postmortem can never mask the original error.
            if _memory.is_resource_exhausted(e):
                _memory.oom_postmortem(e, extra={"phase": "Model.fit"})
            # teardown-only hook: a failed fit must not leak callback-held
            # process-global state (ProfilerCallback's armed span session),
            # but on_train_end keeps its success-only semantics (e.g.
            # ModelCheckpoint's 'final' save). CallbackList.on_train_abort
            # isolates per-callback errors so none can mask the in-flight
            # training exception.
            cbks.on_train_abort()
            raise
        finally:
            self._audit_collect = False
            self._sync_state_to_network()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, prefetch=None,
                 _inside_fit=False):
        loader = self._as_loader(eval_data, batch_size, False, num_workers,
                                 False)
        self.network.eval()
        if self._static() is None:
            if self._params is None:
                self._sync_state_from_network()
            self._eval_step_fn = None  # re-trace in eval mode
        for m in self._metrics:
            m.reset()
        cbks = callbacks if _inside_fit else config_callbacks(
            callbacks, model=self, verbose=verbose,
            metrics=self._metric_names())
        cbks.on_eval_begin()
        total_loss, n = 0.0, 0
        data_iter = self._maybe_prefetch(loader, prefetch)
        for step, batch in enumerate(data_iter):
            cbks.on_eval_batch_begin(step)
            inputs, labels = self._split_batch(batch)
            result = self.eval_batch(inputs, labels)
            loss = result[0] if isinstance(result, tuple) else result
            total_loss += loss
            n += 1
            cbks.on_eval_batch_end(step, self._pack_logs(result))
        logs = {"loss": total_loss / max(1, n)}
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = m.accumulate()
            vals = vals if isinstance(vals, list) else [vals]
            logs.update(dict(zip(names, vals)))
        cbks.on_eval_end(logs)
        self.network.train()
        self._eval_step_fn = None  # next eval retraces with train=False
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        loader = self._as_loader(test_data, batch_size, False, num_workers,
                                 False)
        self.network.eval()
        if self._static() is None:
            if self._params is None:
                self._sync_state_from_network()
            self._eval_step_fn = None
        outputs = []
        for batch in loader:
            inputs, _ = self._split_batch(batch, predict=True)
            outs = self.predict_batch(inputs)
            outputs.append(outs)
        self.network.train()
        self._eval_step_fn = None
        if not outputs:
            return []
        n_out = len(outputs[0])
        grouped = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            grouped = [np.concatenate(g) for g in grouped]
        return grouped

    def _split_batch(self, batch, predict=False):
        if not isinstance(batch, (list, tuple)):
            return [batch], []
        batch = list(batch)
        if predict:
            # without an explicit inputs spec, a (sample, label) dataset
            # feeds only the sample (the reference relies on the spec too)
            n_in = len(self._inputs) if self._inputs else \
                (1 if len(batch) > 1 else len(batch))
            return batch[:n_in], []
        n_in = len(self._inputs) if self._inputs else len(batch) - 1
        n_in = max(1, n_in)
        return batch[:n_in], batch[n_in:]

    def _pack_logs(self, result):
        if isinstance(result, tuple):
            loss, metrics = result
        else:
            loss, metrics = result, []
        logs = {"loss": float(np.asarray(loss).ravel()[0])}
        for m, r in zip(self._metrics, metrics):
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = r if isinstance(r, list) else [r]
            logs.update({k: float(np.asarray(v).ravel()[0])
                         for k, v in zip(names, vals)})
        return logs

    def _metric_names(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    def dump_numerics(self, path=None):
        """On-demand snapshot of the training numerics flight recorder
        (ring tail, anomalies, scaler state, monitor snapshot, memory
        postmortem path) as JSON — the operator surface mirroring
        ``GenerationEngine.dump_flight_recorder``. Returns the file
        path, or ``None`` when numerics was never armed on this
        Model."""
        rec = self._numerics_recorder
        if rec is None:
            return None
        return rec.postmortem(None, path=path, context={
            "site": getattr(getattr(self, "_probe_site", None), "name",
                            None)})

    # -- persistence ---------------------------------------------------------
    def save(self, path, training=True):
        self._sync_state_to_network()
        if not training:
            # reference hapi/model.py save(training=False): export the
            # inference artifact instead of raw weights. jit.save owns
            # the eval-capture/mode-restore dance.
            from .. import jit
            jit.save(self.network, path, input_spec=self._inputs or None)
            return
        _save(self.network.state_dict(), path + ".pdparams")
        if self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = _load(path + ".pdparams")
        self.network.set_state_dict(state)
        self._params = None  # force re-sync
        self._train_step_fn = None
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(_load(opt_path))
            self._opt_state = None
            # checkpoints written after fit() carry tree-named slots;
            # arm the adoption bridge (Optimizer._ensure_slots) so an
            # eager opt.step() straight after load migrates them instead
            # of bias-correcting fresh zeros at the carried step count
            self._optimizer._slot_aliases = {
                p.name: n for n, p in self.network.named_parameters()}

    def parameters(self, *args, **kwargs):
        self._sync_state_to_network()
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        n_params = sum(int(np.prod(p.shape))
                       for p in self.network.parameters())
        lines = [repr(self.network),
                 f"Total params: {n_params:,}"]
        s = "\n".join(lines)
        print(s)
        return {"total_params": n_params}
