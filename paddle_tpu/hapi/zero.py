"""ZeRO-1 cross-replica sharding of the weight update + quantized
gradient collectives for the donated train step.

Per "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arXiv 2004.13336): pure data parallelism keeps the FULL
optimizer state and runs the FULL weight update on every replica — at
dp-way replication that is dp identical copies of the Adam moments and
dp identical update sweeps. Sharding both across the dp axis changes no
math: reduce-scatter the gradients (each replica receives the summed
1/dp stripe it owns), run the optimizer over that stripe against its
stripe of the optimizer state, and all-gather the updated parameter
stripes. Train-state HBM for the optimizer drops by ~dp and the wire
cost is the same as an all-reduce (reduce-scatter + all-gather IS the
two-phase all-reduce decomposition).

Loss contract — the standard data-parallel one: gradients are AVERAGED
across replicas, exact when the loss is an equal-weight mean over the
batch axis (every built-in loss's default reduction). A
``reduction='sum'`` loss, or a mean whose per-sample weights land
unevenly across slices (``ignore_index`` clustered in one slice),
trains under the dp-averaged semantics — identical to
``paddle.DataParallel``/DDP, but not to the single-process run.

The flat layout: the trainable parameter tree is flattened (f32,
deterministic name order) into one vector, padded so it splits into dp
equal stripes whose length is also a multiple of the quantization chunk
— the "padding map" that makes uneven trees shard evenly. Optimizer
slots live as flat [padded] f32 arrays device-sharded
``NamedSharding(mesh, P("dp"))`` end-to-end; ``gather_opt_state`` /
``shard_opt_state`` convert to/from the named {"step", "slots"} layout
at the fit boundary (state_dict/save/load and the eager bridge).

Quantized gradient exchange (``grad_comm='int8'``, EQuARX-style,
arXiv 2506.17615): instead of a f32 reduce-scatter, each replica
quantizes its flat gradient per chunk (max-abs scale / 127, computed
in-step), all-to-alls the int8 payload + f32 scales over the dp axis,
and dequantizes-then-sums locally — ~4x fewer wire bytes on the
gradient exchange (int8 payload + 1/chunk scale overhead vs f32). A
nonfinite gradient POISONS its chunk's scale (max-abs propagates
inf/NaN), so dequantization re-materializes the nonfiniteness and the
PR-9 numerics sentinel still blames the exact step.

Everything here is either host-side layout bookkeeping (numpy) or jnp
code traced into the donated train step by
``hapi/model.py _build_zero_train_step``; nothing syncs.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["FlatLayout", "resolve_mesh", "shard_opt_state",
           "gather_opt_state", "is_sharded_state",
           "quantized_reduce_scatter", "replicate_buffers",
           "time_step_collectives", "QUANT_CHUNK", "AXIS"]

# per-chunk scale granularity of the int8 exchange: 256 elements per
# f32 scale = 1/64 relative overhead on the quantized payload
QUANT_CHUNK = 256

# the mesh axis name the sharded train step communicates over
AXIS = "dp"


class FlatLayout:
    """The padding map: a named (trainable) parameter tree flattened to
    one f32 vector split into dp equal stripes.

    * ``names`` — sorted parameter names (the deterministic flatten
      order; matches the dict-pytree order jax uses).
    * ``offsets[name] = (start, end)`` — the param's slice of the flat
      vector.
    * ``total``/``padded``/``stripe`` — logical element count, padded
      count (a multiple of ``dp * chunk`` so stripes split evenly AND
      each stripe chunks evenly for quantization), per-replica stripe
      length.

    Padding elements carry zero gradients and zero parameters forever
    (every built-in rule maps (p=0, g=0) → 0 up to weight decay of 0),
    so the pad never leaks into real values.
    """

    __slots__ = ("names", "shapes", "dtypes", "sizes", "offsets",
                 "total", "padded", "stripe", "dp", "chunk")

    def __init__(self, names, shapes, dtypes, sizes, offsets, total,
                 padded, stripe, dp, chunk):
        self.names = names
        self.shapes = shapes
        self.dtypes = dtypes
        self.sizes = sizes
        self.offsets = offsets
        self.total = total
        self.padded = padded
        self.stripe = stripe
        self.dp = dp
        self.chunk = chunk

    @staticmethod
    def build(params: Dict[str, object], dp: int,
              chunk: int = QUANT_CHUNK) -> "FlatLayout":
        if dp < 1:
            raise ValueError(f"dp must be >= 1, got {dp}")
        names = sorted(params)
        shapes = {n: tuple(params[n].shape) for n in names}
        dtypes = {n: np.dtype(str(params[n].dtype)) for n in names}
        sizes = {n: int(np.prod(shapes[n])) if shapes[n] else 1
                 for n in names}
        offsets, pos = {}, 0
        for n in names:
            offsets[n] = (pos, pos + sizes[n])
            pos += sizes[n]
        total = pos
        align = dp * max(1, int(chunk))
        padded = max(align, ((total + align - 1) // align) * align)
        return FlatLayout(names, shapes, dtypes, sizes, offsets, total,
                          padded, padded // dp, int(dp), int(chunk))

    def compatible_with(self, params: Dict[str, object]) -> bool:
        """True when ``params`` flattens to exactly this layout — the
        staleness probe for a cached sharded opt state."""
        if sorted(params) != self.names:
            return False
        return all(tuple(params[n].shape) == self.shapes[n]
                   for n in self.names)

    # -- traced (jnp) helpers ---------------------------------------------
    def flatten(self, tree):
        """Concat the tree's leaves (f32, name order) + the pad tail.
        jnp code — traced into the step."""
        import jax.numpy as jnp
        parts = [jnp.reshape(tree[n], (-1,)).astype(jnp.float32)
                 for n in self.names]
        flat = jnp.concatenate(parts) if parts \
            else jnp.zeros((0,), jnp.float32)
        return jnp.pad(flat, (0, self.padded - self.total))

    def unflatten(self, flat, like: Dict[str, object]):
        """Split a flat f32 vector back into the named tree, cast to
        each param's dtype. jnp code — traced into the step."""
        import jax.numpy as jnp
        out = {}
        for n in self.names:
            lo, hi = self.offsets[n]
            out[n] = jnp.reshape(flat[lo:hi], self.shapes[n]).astype(
                like[n].dtype)
        return out

    # -- host-side helpers -------------------------------------------------
    def flatten_host(self, tree: Dict[str, object],
                     default: float = 0.0) -> np.ndarray:
        """numpy flatten (missing names fall back to ``default``) — the
        shard/gather boundary runs on host, never inside a trace."""
        flat = np.full((self.padded,), default, np.float32)
        for n in self.names:
            v = tree.get(n)
            if v is None:
                continue
            lo, hi = self.offsets[n]
            flat[lo:hi] = np.asarray(v, np.float32).reshape(-1)
        return flat

    def split_host(self, flat: np.ndarray) -> Dict[str, np.ndarray]:
        flat = np.asarray(flat, np.float32).reshape(-1)
        out = {}
        for n in self.names:
            lo, hi = self.offsets[n]
            out[n] = flat[lo:hi].reshape(self.shapes[n])
        return out

    def group_ids(self, audit_layout) -> np.ndarray:
        """Per-element layer-group index for the sharded numerics audit
        (profiler/numerics.build_audit_flat): element → the index of
        its param's group in ``audit_layout.groups``; padding gets the
        extra ``n_groups`` bucket, which the audit drops."""
        n_groups = len(audit_layout.groups)
        ids = np.full((self.padded,), n_groups, np.int32)
        by_name = {}
        for gi, g in enumerate(audit_layout.groups):
            for member in audit_layout.members[g]:
                by_name[member] = gi
        for n in self.names:
            gi = by_name.get(n)
            if gi is None:
                continue
            lo, hi = self.offsets[n]
            ids[lo:hi] = gi
        return ids

    def mask_from(self, names: Sequence[str]) -> np.ndarray:
        """0/1 f32 per-element mask selecting the given params — the
        flat carrier of per-param predicates (AdamW's decoupled-decay
        exclusion) into the stripe-local update rule."""
        mask = np.zeros((self.padded,), np.float32)
        for n in names:
            if n in self.offsets:
                lo, hi = self.offsets[n]
                mask[lo:hi] = 1.0
        return mask

    def t0_vector(self, t0_map: Dict[str, int]) -> np.ndarray:
        """Per-element birth-step vector (flat analog of the ``_t0``
        slot marker): step-dependent rules see ``step - t0`` per
        element, so a param unfrozen mid-run bias-corrects from its own
        t=0 inside the flat stripe exactly as it does in the named
        path."""
        t0 = np.zeros((self.padded,), np.int32)
        for n, v in t0_map.items():
            if n in self.offsets:
                lo, hi = self.offsets[n]
                t0[lo:hi] = int(v)
        return t0

    def __repr__(self):
        return (f"<FlatLayout params={len(self.names)} total={self.total} "
                f"padded={self.padded} dp={self.dp} stripe={self.stripe}>")


def resolve_mesh(min_dp: int = 2):
    """The dp mesh the sharded step runs over: the globally registered
    mesh (``distributed.env.build_mesh``) when its single axis is
    ``'dp'`` — the way tests and launchers pick dp < device_count —
    else a fresh 1-D mesh over every local device. Raises when fewer
    than ``min_dp`` devices are available: a 1-device "sharded" step
    would silently measure nothing."""
    import jax
    from jax.sharding import Mesh

    from ..distributed import env
    mesh = env.get_mesh()
    if mesh is not None and tuple(mesh.axis_names) == (AXIS,):
        if int(np.prod(mesh.devices.shape)) >= min_dp:
            return mesh
    devices = jax.devices()
    if len(devices) < min_dp:
        raise ValueError(
            f"fit(zero=1) needs a data-parallel mesh of >= {min_dp} "
            f"devices but only {len(devices)} are visible; on CPU set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=N or "
            f"register a mesh with distributed.env.build_mesh("
            f"{{'dp': N}})")
    return Mesh(np.array(devices), (AXIS,))


def dp_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P(AXIS))


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P())


def is_sharded_state(state) -> bool:
    """True for the sharded opt-state layout ({"step", "flat": {...}})
    vs the named layout ({"step", "slots": {...}})."""
    return isinstance(state, dict) and "flat" in state


def shard_opt_state(named_state: dict, layout: FlatLayout, mesh,
                    slot_names: Sequence[str]) -> dict:
    """Named {"step", "slots": {name: {slot: arr}}} → sharded {"step",
    "flat": {slot: [padded] f32 P('dp')}}. Missing per-param slots
    (e.g. a param adopted without moments) stripe in as zeros. The
    ``_t0`` birth markers are NOT carried here — they are per-param
    host ints the Model keeps beside the layout (``Model._zero_t0``)
    and bakes into the step as a flat constant."""
    import jax
    import jax.numpy as jnp

    slots_in = named_state.get("slots", {})
    shard = dp_sharding(mesh)
    flat = {}
    for s in slot_names:
        host = layout.flatten_host(
            {n: slots_in.get(n, {}).get(s) for n in layout.names})
        flat[s] = jax.device_put(jnp.asarray(host), shard)
    return {"step": jnp.asarray(np.asarray(named_state["step"]),
                                jnp.int32),
            "flat": flat}


def gather_opt_state(sharded_state: dict, layout: FlatLayout,
                     slot_names: Sequence[str]) -> dict:
    """Sharded → named (one host fetch per slot; runs at fit
    boundaries — state_dict/save/the eager bridge — never per step)."""
    import jax.numpy as jnp

    slots: Dict[str, Dict[str, object]] = {n: {} for n in layout.names}
    for s in slot_names:
        arr = sharded_state["flat"].get(s)
        if arr is None:
            continue
        split = layout.split_host(np.asarray(arr))
        for n in layout.names:
            slots[n][s] = jnp.asarray(split[n])
    return {"step": jnp.asarray(int(np.asarray(sharded_state["step"])),
                                jnp.int32),
            "slots": slots}


# ---------------------------------------------------------------------------
# traced collectives of the sharded step
# ---------------------------------------------------------------------------

def quantized_reduce_scatter(flat_g, axis_name: str, dp: int,
                             stripe: int, chunk: int):
    """EQuARX-style int8 gradient exchange: returns this replica's SUM
    stripe (caller divides by dp for the mean), numerically
    ``psum_scatter`` with per-chunk max-abs quantization on the wire.

    Each replica chunks its [dp, stripe/chunk, chunk] view, computes
    f32 scales (max-abs/127, floored so all-zero chunks stay exactly
    zero), quantizes to int8, and exchanges shards with one all_to_all
    for the payload and one for the scales — both through the
    byte-counted ``collective`` wrappers, so the wire savings show up
    in the ``collective_bytes/*`` counters and profiler spans. A
    nonfinite element drives its chunk's scale nonfinite, and
    ``int8 * nonfinite-scale`` dequantizes nonfinite — corruption is
    never silently rounded away (the PR-9 sentinel fires at the exact
    step)."""
    import jax.numpy as jnp

    from ..distributed import collective

    n_chunks = stripe // chunk
    g3 = flat_g.reshape(dp, n_chunks, chunk)
    scales = jnp.max(jnp.abs(g3), axis=-1) / jnp.float32(127.0)
    scales = jnp.maximum(scales, jnp.float32(1e-30))
    q = jnp.clip(jnp.round(g3 / scales[..., None]),
                 -127.0, 127.0).astype(jnp.int8)
    # shard j of every replica lands on replica j (tiled all_to_all on
    # the leading dp axis); received row r = replica r's contribution
    # to MY stripe
    q_recv = collective.all_to_all_in_axis(q, axis_name,
                                           split_axis=0, concat_axis=0)
    s_recv = collective.all_to_all_in_axis(scales, axis_name,
                                           split_axis=0, concat_axis=0)
    deq = q_recv.astype(jnp.float32) * s_recv[..., None]
    return jnp.sum(deq, axis=0).reshape(stripe)


def replicate_buffers(buffers, axis_name: str, dp: int):
    """Make per-replica buffer updates (BN running stats computed from
    the LOCAL batch slice) consistent across the dp axis: floats are
    cross-replica means (equal-sized slices → the full-batch mean for
    mean-style stats), integers (step counters) are identical on every
    replica so psum/dp is exact."""
    import jax
    import jax.numpy as jnp

    def one(b):
        if jnp.issubdtype(b.dtype, jnp.inexact):
            return jax.lax.pmean(b, axis_name)
        # psum promotes (bool -> int32); cast back so fit(zero=1)
        # never rewrites a buffer dtype the replicated step preserves
        # (dtype drift = a spurious signature retrace + a checkpoint
        # that stops being byte-identical to the replicated format)
        return (jax.lax.psum(b, axis_name) // dp).astype(b.dtype)

    return {k: one(v) for k, v in buffers.items()}


# ---------------------------------------------------------------------------
# collective device timing (ISSUE 13): price the exchange, not just its
# bytes
# ---------------------------------------------------------------------------

# (mesh shape, axis names, padded length, grad_comm) -> list of warmed
# probe entries (kind, payload_bytes, compiled_fn, operands)
_PROBE_CACHE: Dict[tuple, list] = {}


def time_step_collectives(mesh, layout: "FlatLayout",
                          grad_comm: str = "fp32") -> Dict[str, float]:
    """Sampled device timing of the ZeRO step's collective pair.

    The in-step reduce-scatter and all-gather are fused inside ONE
    donated XLA program — no host timer can bracket them there, and a
    device trace needs an armed profiler session. So this probe runs
    each kind ISOLATED, in a tiny jitted ``shard_map`` over the SAME
    mesh axis and the SAME flat payload shape as the real exchange
    (``layout.padded`` f32 in, one ``layout.stripe`` per replica out,
    and the int8 all_to_all pair under ``grad_comm='int8'``), warmed
    once per shape so compile never pollutes a sample, then bracketed
    with ``block_until_ready``. The result feeds
    ``collective_time_ms/<kind>`` + ``collective_bw_gbps/<kind>``
    (distributed/collective.py) and is the EXPOSED cost of the
    exchange: the zero step currently brackets it serially, so this is
    what full overlap (the ROADMAP follow-on) would reclaim —
    ``communication_report()`` joins it against ``hapi/step_time_ms``.

    Called by ``Model.fit``'s zero dispatch path under the
    FLAGS_collective_timing sampling stride (first step always); cheap
    enough that the stride, not the probe, is the budget knob. Returns
    ``{kind: ms}`` for the kinds probed."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..distributed import collective as _coll

    key = (tuple(int(s) for s in mesh.devices.shape),
           tuple(mesh.axis_names), int(layout.padded), str(grad_comm))
    probes = _PROBE_CACHE.get(key)
    if probes is None:
        dp, stripe, padded = layout.dp, layout.stripe, layout.padded
        f32 = jnp.float32
        probes = []

        def sm(fn, in_specs, out_specs):
            return jax.jit(jax.shard_map(
                fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False))

        rs = sm(lambda x: jax.lax.psum_scatter(
            x, AXIS, scatter_dimension=0, tiled=True), P(), P(AXIS))
        probes.append(("reduce_scatter", padded * 4, rs,
                       (jnp.zeros((padded,), f32),)))
        ag = sm(lambda x: jax.lax.all_gather(x, AXIS, axis=0, tiled=True),
                P(AXIS), P())
        probes.append(("all_gather", padded * 4, ag,
                       (jnp.zeros((padded,), f32),)))
        if grad_comm == "int8":
            # the int8 path replaces psum_scatter with an all_to_all of
            # int8 payload + f32 per-chunk scales; probe that wire shape
            n_scales = padded // layout.chunk

            def a2a(q, s):
                qr = jax.lax.all_to_all(
                    q.reshape(dp, stripe), AXIS, split_axis=0,
                    concat_axis=0, tiled=True)
                sr = jax.lax.all_to_all(
                    s.reshape(dp, n_scales // dp), AXIS, split_axis=0,
                    concat_axis=0, tiled=True)
                return qr, sr
            probes.append((
                "all_to_all", padded + n_scales * 4,
                sm(a2a, (P(), P()), (P(AXIS), P(AXIS))),
                (jnp.zeros((padded,), jnp.int8),
                 jnp.zeros((n_scales,), f32))))
        # warm every probe once: the sample must price the collective,
        # never its compile
        for _, _, fn, operands in probes:
            jax.block_until_ready(fn(*operands))
        _PROBE_CACHE[key] = probes

    import time
    out: Dict[str, float] = {}
    for kind, nbytes, fn, operands in probes:
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*operands))
        ms = (time.perf_counter() - t0) * 1e3
        _coll.observe_collective_time(kind, ms, nbytes)
        out[kind] = ms
    # tell the report which kinds the LIVE step actually pays per step:
    # int8 replaces the fp32 reduce-scatter with the all_to_all pair,
    # so the probed reduce_scatter is a comparison figure, not a cost
    _coll.note_step_exchange(
        ("all_to_all", "all_gather") if grad_comm == "int8"
        else ("reduce_scatter", "all_gather"))
    return out
