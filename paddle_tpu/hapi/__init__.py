"""hapi — high-level training API (``paddle.Model``).

Analog of the reference's ``python/paddle/hapi/``.
"""
from . import callbacks  # noqa: F401
from .model import Model  # noqa: F401
