"""``paddle.summary`` / ``paddle.flops`` (reference: python/paddle/hapi/
model_summary.py, dynamic_flops.py) — per-layer output shapes + parameter
counts via forward hooks, and a FLOP estimate via XLA cost analysis."""
from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["summary", "flops"]


def _spec_to_input(input_size, dtypes):
    import paddle_tpu as paddle
    if isinstance(input_size, (list, tuple)) and input_size and \
            isinstance(input_size[0], (list, tuple)):
        sizes = list(input_size)
    else:
        sizes = [tuple(input_size)]
    dtypes = dtypes or ["float32"] * len(sizes)
    if isinstance(dtypes, str):
        dtypes = [dtypes] * len(sizes)
    outs = []
    for shape, dt in zip(sizes, dtypes):
        shape = tuple(1 if (s in (-1, None)) else int(s) for s in shape)
        if str(dt).startswith("int"):
            arr = np.zeros(shape, dtype=dt)
        else:
            arr = np.random.uniform(-1, 1, shape).astype(dt)
        outs.append(paddle.to_tensor(arr))
    return outs


def summary(net, input_size=None, dtypes=None, input=None):
    """Layer-by-layer table: output shape + param count (reference:
    hapi/model_summary.py summary)."""
    import paddle_tpu as paddle

    rows = []
    handles = []

    def make_hook(name, layer):
        def hook(lyr, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (list, tuple)) \
                else outputs
            shape = list(getattr(out, "shape", []))
            n_params = sum(
                int(np.prod(p.shape))
                for p in lyr._parameters.values() if p is not None) \
                if hasattr(lyr, "_parameters") else 0
            rows.append((name or type(lyr).__name__,
                         type(lyr).__name__, shape, n_params))

        return hook

    subs = list(net.named_sublayers())
    if not subs:
        # the model is itself a leaf layer: report it directly
        handles.append(net.register_forward_post_hook(
            make_hook(type(net).__name__, net)))
    for name, sub in subs:
        # leaf layers only — container shapes repeat their children
        if next(iter(sub.named_sublayers()), None) is None:
            handles.append(sub.register_forward_post_hook(
                make_hook(name, sub)))

    was_training = net.training
    net.eval()
    try:
        if input is not None:
            args = input if isinstance(input, (list, tuple)) else [input]
        else:
            if input_size is None:
                raise ValueError("summary needs input_size or input")
            args = _spec_to_input(input_size, dtypes)
        with paddle.no_grad():
            net(*args)
    finally:
        for h in handles:
            h.remove()
        if was_training:
            net.train()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)
    w_name = max([len(r[0]) + len(r[1]) + 3 for r in rows] + [20])
    lines = ["-" * (w_name + 40),
             f"{'Layer (type)':<{w_name}} {'Output Shape':<22} "
             f"{'Param #':>12}",
             "=" * (w_name + 40)]
    for name, ltype, shape, n_params in rows:
        lines.append(f"{name + ' (' + ltype + ')':<{w_name}} "
                     f"{str(shape):<22} {n_params:>12,}")
    lines += ["=" * (w_name + 40),
              f"Total params: {total:,}",
              f"Trainable params: {trainable:,}",
              f"Non-trainable params: {total - trainable:,}",
              "-" * (w_name + 40)]
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size=None, inputs=None, custom_ops=None,
          print_detail: bool = False) -> int:
    """FLOPs of one forward pass, measured by XLA's cost analysis over
    the traced program (reference: hapi/dynamic_flops.py counts
    per-layer by hand; the compiler already knows). Routed through
    ``framework/program_registry.analyze_callable`` — the same helper
    behind ``cost_model.estimate_flops`` and every registry site.
    Returns ``-1`` when the backend provides no analysis (the reference
    API contract is an int; ``estimate_flops`` returns ``None`` for the
    same case)."""
    import paddle_tpu as paddle
    from ..framework.program_registry import analyze_callable
    from ..nn.layer.layers import functional_call, get_params_tree

    if inputs is not None:
        args = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    else:
        if input_size is None:
            raise ValueError("flops needs input_size or inputs")
        args = _spec_to_input(input_size, None)
    params = get_params_tree(net)
    arrs = [a._data for a in args]

    def fwd(p, *xs):
        out, _ = functional_call(net, p, {},
                                 *[paddle.Tensor(x) for x in xs])
        first = out[0] if isinstance(out, (list, tuple)) else out
        return first._data

    res = analyze_callable(fwd, params, *arrs)
    total = -1 if res is None or res.get("flops") is None \
        else int(res["flops"])
    if print_detail:
        print(f"Total Flops: {total}")
    return total
