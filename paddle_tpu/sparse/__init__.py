"""``paddle.sparse`` — COO/CSR sparse tensors and math.

Reference: python/paddle/incubate/sparse/ (creation.py sparse_coo_tensor /
sparse_csr_tensor, unary.py, binary.py math, nn/) over the phi
SparseCooTensor/SparseCsrTensor kernels (paddle/phi/kernels/sparse/).

TPU-native: storage is ``jax.experimental.sparse`` BCOO/BCSR — batched
COO with static nse, which is the XLA-compatible sparse format (dynamic
nnz is hostile to the compiler; the reference's dynamic-shape sparse
kernels have no TPU analog). Elementwise math maps onto the values;
spmm lowers through ``bcoo_dot_general``.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..framework.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "is_sparse", "add", "subtract", "multiply",
           "divide", "matmul", "masked_matmul", "relu", "sqrt", "sin",
           "tanh", "abs", "pow", "neg", "cast", "to_dense", "nn"]


def _bcoo():
    from jax.experimental import sparse as jsparse
    return jsparse


class SparseCooTensor:
    """COO sparse tensor (reference phi::SparseCooTensor)."""

    def __init__(self, bcoo):
        self._mat = bcoo

    # -- construction ------------------------------------------------------
    @classmethod
    def from_parts(cls, indices, values, shape):
        import jax.numpy as jnp
        jsparse = _bcoo()
        idx = jnp.asarray(indices)
        vals = jnp.asarray(values)
        if idx.ndim != 2:
            raise ValueError("indices must be [sparse_ndim, nnz]")
        mat = jsparse.BCOO((vals, idx.T), shape=tuple(shape))
        return cls(mat)

    # -- paddle API --------------------------------------------------------
    @property
    def shape(self):
        return list(self._mat.shape)

    @property
    def dtype(self):
        return self._mat.dtype

    def nnz(self) -> int:
        return int(self._mat.nse)

    def indices(self) -> Tensor:
        return Tensor(self._mat.indices.T)

    def values(self) -> Tensor:
        # ops that thread the eager autograd tape (sparse/nn.py conv/norm)
        # stash their tape-connected values Tensor here so training flows
        vt = getattr(self, "_values_tensor", None)
        return vt if vt is not None else Tensor(self._mat.data)

    def to_dense(self) -> Tensor:
        return Tensor(self._mat.todense())

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._mat.sum_duplicates())

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    def _map_values(self, fn) -> "SparseCooTensor":
        jsparse = _bcoo()
        mat = jsparse.BCOO((fn(self._mat.data), self._mat.indices),
                           shape=self._mat.shape)
        return SparseCooTensor(mat)


class SparseCsrTensor:
    """CSR sparse tensor (reference phi::SparseCsrTensor)."""

    def __init__(self, bcsr):
        self._mat = bcsr

    @classmethod
    def from_parts(cls, crows, cols, values, shape):
        import jax.numpy as jnp
        jsparse = _bcoo()
        mat = jsparse.BCSR(
            (jnp.asarray(values), jnp.asarray(cols),
             jnp.asarray(crows)), shape=tuple(shape))
        return cls(mat)

    @property
    def shape(self):
        return list(self._mat.shape)

    @property
    def dtype(self):
        return self._mat.dtype

    def nnz(self) -> int:
        return int(self._mat.nse)

    def crows(self) -> Tensor:
        return Tensor(self._mat.indptr)

    def cols(self) -> Tensor:
        return Tensor(self._mat.indices)

    def values(self) -> Tensor:
        return Tensor(self._mat.data)

    def to_dense(self) -> Tensor:
        return Tensor(self._mat.todense())

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """Reference: incubate/sparse/creation.py sparse_coo_tensor."""
    import jax.numpy as jnp
    idx = np.asarray(indices if not isinstance(indices, Tensor)
                     else indices.numpy())
    vals = values.numpy() if isinstance(values, Tensor) else \
        np.asarray(values)
    if dtype is not None:
        from ..framework.dtypes import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    return SparseCooTensor.from_parts(idx, vals, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    """Reference: incubate/sparse/creation.py sparse_csr_tensor."""
    vals = values.numpy() if isinstance(values, Tensor) else \
        np.asarray(values)
    if dtype is not None:
        from ..framework.dtypes import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    crows = crows.numpy() if isinstance(crows, Tensor) else \
        np.asarray(crows)
    cols = cols.numpy() if isinstance(cols, Tensor) else np.asarray(cols)
    return SparseCsrTensor.from_parts(crows, cols, vals, shape)


def is_sparse(x) -> bool:
    return isinstance(x, (SparseCooTensor, SparseCsrTensor))


def to_dense(x):
    return x.to_dense() if is_sparse(x) else x


# ---------------------------------------------------------------------------
# math (reference incubate/sparse/{unary,binary}.py)
# ---------------------------------------------------------------------------

def _same_pattern(a: SparseCooTensor, b: SparseCooTensor) -> bool:
    import jax.numpy as jnp
    ia, ib = a._mat.indices, b._mat.indices
    return ia.shape == ib.shape and bool(jnp.all(ia == ib))


def _binary(a, b, fn):
    jsparse = _bcoo()
    if isinstance(a, SparseCooTensor) and isinstance(b, SparseCooTensor):
        if _same_pattern(a, b):
            mat = jsparse.BCOO((fn(a._mat.data, b._mat.data),
                                a._mat.indices), shape=a._mat.shape)
            return SparseCooTensor(mat)
        # differing patterns: densify (the reference's kernels merge
        # patterns; under static shapes densify is the honest fallback)
        return Tensor(fn(a._mat.todense(), b._mat.todense()))
    da = a._mat.todense() if is_sparse(a) else (
        a._data if isinstance(a, Tensor) else a)
    db = b._mat.todense() if is_sparse(b) else (
        b._data if isinstance(b, Tensor) else b)
    return Tensor(fn(da, db))


def add(a, b):
    return _binary(a, b, lambda x, y: x + y)


def subtract(a, b):
    return _binary(a, b, lambda x, y: x - y)


def multiply(a, b):
    return _binary(a, b, lambda x, y: x * y)


def divide(a, b):
    return _binary(a, b, lambda x, y: x / y)


def matmul(a, b):
    """sparse @ dense (reference sparse/binary.py matmul) via
    bcoo_dot_general — the spmm path XLA can fuse."""
    import jax.numpy as jnp
    db = b._data if isinstance(b, Tensor) else jnp.asarray(b)
    if isinstance(a, SparseCsrTensor):
        a = SparseCooTensor(a._mat.to_bcoo())
    if isinstance(a, SparseCooTensor):
        jsparse = _bcoo()
        out = jsparse.bcoo_dot_general(
            a._mat, db,
            dimension_numbers=(((a._mat.ndim - 1,), (0,)), ((), ())))
        return Tensor(out)
    da = a._data if isinstance(a, Tensor) else jnp.asarray(a)
    return Tensor(da @ db)


def masked_matmul(x, y, mask):
    """dense @ dense sampled at mask's pattern (reference
    sparse/binary.py masked_matmul — SDDMM)."""
    import jax.numpy as jnp
    jsparse = _bcoo()
    dx = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    dy = y._data if isinstance(y, Tensor) else jnp.asarray(y)
    if not isinstance(mask, SparseCooTensor):
        raise TypeError("mask must be a SparseCooTensor")
    idx = mask._mat.indices          # [nnz, 2]
    rows, cols = idx[:, 0], idx[:, 1]
    vals = (dx[rows, :] * dy[:, cols].T).sum(-1)
    return SparseCooTensor(jsparse.BCOO((vals, idx),
                                        shape=mask._mat.shape))


def _unary(name, value_fn_name):
    """value_fn_name resolves lazily inside the call so this module (and
    paddle_tpu's eager import of it) never forces the jax stack in."""

    def value_fn(v):
        import jax
        import jax.numpy as jnp
        table = {"relu": jax.nn.relu, "sqrt": jnp.sqrt, "sin": jnp.sin,
                 "tanh": jnp.tanh, "abs": jnp.abs,
                 "neg": lambda a: -a}
        return table[value_fn_name](v)

    def op(x):
        if isinstance(x, SparseCooTensor):
            return x._map_values(value_fn)
        if isinstance(x, SparseCsrTensor):
            jsparse = _bcoo()
            mat = jsparse.BCSR((value_fn(x._mat.data), x._mat.indices,
                                x._mat.indptr), shape=x._mat.shape)
            return SparseCsrTensor(mat)
        from ..framework.dispatch import call_op
        return call_op(name, x)
    op.__name__ = name
    return op


relu = _unary("relu", "relu")
sqrt = _unary("sqrt", "sqrt")
sin = _unary("sin", "sin")
tanh = _unary("tanh", "tanh")
abs = _unary("abs", "abs")  # noqa: A001
neg = _unary("neg", "neg")


def pow(x, factor):  # noqa: A001
    if is_sparse(x):
        return x._map_values(lambda v: v ** factor)
    from ..framework.dispatch import call_op
    return call_op("pow", x, y=factor)


def cast(x, index_dtype=None, value_dtype=None):
    from ..framework.dtypes import convert_dtype
    if isinstance(x, SparseCooTensor):
        jsparse = _bcoo()
        idx = x._mat.indices
        vals = x._mat.data
        if index_dtype is not None:
            idx = idx.astype(convert_dtype(index_dtype))
        if value_dtype is not None:
            vals = vals.astype(convert_dtype(value_dtype))
        return SparseCooTensor(jsparse.BCOO((vals, idx),
                                            shape=x._mat.shape))
    raise TypeError("cast expects a SparseCooTensor")


from . import nn  # noqa: E402  (conv3d/pool layers; reference sparse/nn/)
