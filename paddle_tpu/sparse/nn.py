"""``paddle.sparse.nn`` — sparse conv3d / pooling / norm layers.

Reference: python/paddle/incubate/sparse/nn/ (Conv3D, SubmConv3D,
MaxPool3D, ReLU, BatchNorm) over the phi sparse kernel family
(paddle/phi/kernels/sparse/conv_kernel.h, pool_kernel.h): gather-GEMM
-scatter over a rulebook of active sites, NDHWC activations, DHWIO
weights.

TPU-native stance: a rulebook is a data-dependent gather plan — XLA
wants static shapes, and the MXU wants dense tiles. So compute rides the
DENSE conv/pool path (one lax.conv_general_dilated over the densified
block — at the occupancies where sparse conv matters (<5%) the MXU
finishes the dense conv faster than any scalar gather loop a TPU could
run), while SPARSITY lives in the output pattern:

* ``subm_conv3d`` — the submanifold form keeps the INPUT pattern
  (reference subm conv semantics), so nse is static and the whole op is
  jit-compilable end to end: dense conv + gather at the stored indices.
* ``conv3d`` / ``max_pool3d`` — the output pattern is data-dependent
  (any site a kernel window reaches); it is recomputed EAGERLY from the
  dense result's nonzeros, matching the reference's rulebook expansion.
  Inside jit, use the dense result directly (or subm_conv3d).

Gradients flow through values (the dense compute graph); pattern
indices are integer metadata, as in the reference.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..framework.tensor import Tensor
from ..nn.layer.layers import Layer
from . import SparseCooTensor, _bcoo

__all__ = ["conv3d", "subm_conv3d", "max_pool3d", "relu", "batch_norm",
           "Conv3D", "SubmConv3D", "MaxPool3D", "ReLU", "BatchNorm"]


def _norm3(v):
    return (v,) * 3 if isinstance(v, int) else tuple(v)


def _dense_ndhwc(x: SparseCooTensor):
    if not isinstance(x, SparseCooTensor):
        raise TypeError(f"expected SparseCooTensor, got {type(x).__name__}")
    if len(x.shape) != 5:
        raise ValueError(
            f"sparse conv3d expects a 5-D NDHWC tensor, got {x.shape}")
    return x._mat.todense()


def _conv3d_dense(dense, weight, bias, stride, padding, dilation, groups):
    """NDHWC x DHWIO -> NDHWC (the reference sparse-conv weight layout)."""
    import jax.numpy as jnp
    from jax import lax

    w = weight._data if isinstance(weight, Tensor) else jnp.asarray(weight)
    if w.ndim != 5:
        raise ValueError(f"weight must be DHWIO (5-D), got shape {w.shape}")
    pad = _norm3(padding)
    out = lax.conv_general_dilated(
        dense, w, window_strides=_norm3(stride),
        padding=[(p, p) for p in pad],
        rhs_dilation=_norm3(dilation),
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
        feature_group_count=int(groups))
    if bias is not None:
        b = bias._data if isinstance(bias, Tensor) else jnp.asarray(bias)
        out = out + b
    return out


def _sparsify(dense, site_mask) -> SparseCooTensor:
    """Eager re-sparsification at an explicit REACHABILITY mask (the
    reference's output rulebook covers every site a kernel window
    reaches — a reached site whose value happens to be exactly 0 stays
    in the pattern, so downstream subm convs see the same active set)."""
    import jax.numpy as jnp
    idx = np.argwhere(np.asarray(site_mask))         # [nnz, 4] over NDHW
    vals = np.asarray(dense)[tuple(idx.T)]           # [nnz, C]
    # channel axis stays dense: BCOO with n_sparse=4 on a 5-D shape
    mat = _bcoo().BCOO((jnp.asarray(vals), jnp.asarray(idx)),
                       shape=tuple(dense.shape))
    return SparseCooTensor(mat)


def _occupancy(x: SparseCooTensor):
    """Bool [N,D,H,W] marking the active sites of a 5-D sparse tensor."""
    import jax.numpy as jnp
    return jnp.zeros(tuple(x.shape[:-1]), jnp.bool_).at[
        tuple(x._mat.indices.T)].set(True, mode="drop")


def _as_tensor(v, stop_gradient=True):
    import jax.numpy as jnp
    if isinstance(v, Tensor):
        return v
    return Tensor(jnp.asarray(v), stop_gradient=stop_gradient)


def _apply_params(fn, weight, bias):
    """Run ``fn(w[, b]) -> array`` through the eager autograd tape so a
    Tensor weight/bias trains (autograd.differentiable_apply — raw-array
    callers and jitted traces take the plain-call path inside)."""
    from ..autograd import differentiable_apply
    params = [_as_tensor(weight)]
    if bias is not None:
        params.append(_as_tensor(bias))
    return differentiable_apply(fn, *params)


def conv3d(x: SparseCooTensor, weight, bias=None, stride=1, padding=0,
           dilation=1, groups=1, data_format="NDHWC") -> SparseCooTensor:
    """Sparse conv3d (reference sparse/nn/functional/conv.py conv3d).
    Output pattern is recomputed from the result — eager only; inside
    jit use ``subm_conv3d`` (static pattern) or dense conv."""
    if data_format != "NDHWC":
        raise ValueError("sparse conv3d supports NDHWC only (the "
                         "reference's layout)")
    import jax.numpy as jnp
    from jax import lax
    dense = _dense_ndhwc(x)

    def fn(w, b=None):
        return _conv3d_dense(dense, w, b, stride, padding, dilation,
                             groups)

    dense_out = _apply_params(fn, weight, bias)
    # reachability mask: a kernel-window count conv over the occupancy —
    # every reached site joins the pattern even if its value is 0
    w_arr = weight._data if isinstance(weight, Tensor) else \
        jnp.asarray(weight)
    occ = _occupancy(x).astype(jnp.float32)[..., None]
    ones = jnp.ones(tuple(w_arr.shape[:3]) + (1, 1), jnp.float32)
    reached = _conv3d_dense(occ, ones, None, stride, padding,
                            dilation, 1)[..., 0] > 0
    sp = _sparsify(dense_out._data, reached)
    if not dense_out.stop_gradient:
        idx = np.asarray(sp._mat.indices)
        from ..autograd import differentiable_apply
        sp._values_tensor = differentiable_apply(
            lambda d: d[tuple(idx.T)], dense_out)
    return sp


def subm_conv3d(x: SparseCooTensor, weight, bias=None, stride=1,
                padding=0, dilation=1, groups=1,
                data_format="NDHWC") -> SparseCooTensor:
    """Submanifold sparse conv3d (reference subm_conv3d): the output
    pattern IS the input pattern, so nse stays static — fully
    jit-compilable. Requires stride 1 (as the reference's subm conv)."""
    if data_format != "NDHWC":
        raise ValueError("subm_conv3d supports NDHWC only")
    if _norm3(stride) != (1, 1, 1):
        raise ValueError("subm_conv3d requires stride=1 (the submanifold "
                         "pattern is only shape-preserving at stride 1)")
    w_shape = weight.shape if hasattr(weight, "shape") else \
        np.asarray(weight).shape
    for k, p, d in zip(w_shape[:3], _norm3(padding), _norm3(dilation)):
        if 2 * p != (int(k) - 1) * d:
            raise ValueError(
                f"subm_conv3d needs shape-preserving padding: kernel "
                f"{tuple(int(v) for v in w_shape[:3])} with padding "
                f"{_norm3(padding)} dilation {_norm3(dilation)} changes "
                f"the spatial shape, so input-site indexing would read "
                f"out of bounds; use padding=(k-1)*dilation/2 per axis")
    data, idx = x._mat.data, x._mat.indices           # idx: [nnz, 4]
    shape = tuple(x.shape)

    def fn(w, b=None):
        dense = _bcoo().BCOO((data, idx), shape=shape).todense()
        out = _conv3d_dense(dense, w, b, stride, padding, dilation,
                            groups)
        return out[idx[:, 0], idx[:, 1], idx[:, 2], idx[:, 3]]

    vals = _apply_params(fn, weight, bias)
    out_c = int(vals.shape[-1])
    mat = _bcoo().BCOO((vals._data, idx), shape=shape[:-1] + (out_c,))
    sp = SparseCooTensor(mat)
    if not vals.stop_gradient:
        sp._values_tensor = vals
    return sp


def max_pool3d(x: SparseCooTensor, kernel_size, stride=None, padding=0,
               data_format="NDHWC") -> SparseCooTensor:
    """Sparse max pooling (reference sparse/nn/functional/pool.py):
    the max over ACTIVE sites in each window; windows with no active
    site produce no output site."""
    import jax.numpy as jnp
    from jax import lax

    if data_format != "NDHWC":
        raise ValueError("sparse max_pool3d supports NDHWC only")
    dense = _dense_ndhwc(x)
    k = _norm3(kernel_size)
    s = _norm3(stride) if stride is not None else k
    p = _norm3(padding)
    window = (1,) + k + (1,)
    strides = (1,) + s + (1,)
    pads = ((0, 0),) + tuple((pp, pp) for pp in p) + ((0, 0),)
    neg = jnp.asarray(-jnp.inf, dense.dtype)
    # occupancy mask: only active sites compete in the max (an all-negative
    # active site must still win over inactive zeros)
    occ = _occupancy(x)[..., None]
    masked = jnp.where(occ, dense, neg)
    pooled = lax.reduce_window(masked, neg, lax.max, window, strides, pads)
    any_active = lax.reduce_window(
        occ, False, lambda a, b: jnp.logical_or(a, b), window, strides,
        pads)
    pooled = jnp.where(any_active, pooled, 0)
    # pattern = windows that saw an active site — NOT value != 0, so an
    # active window whose max is exactly 0 keeps its site
    return _sparsify(pooled, any_active[..., 0])


def relu(x: SparseCooTensor) -> SparseCooTensor:
    from . import relu as _relu
    return _relu(x)


def batch_norm(x: SparseCooTensor, mean, variance, weight, bias,
               epsilon=1e-5) -> SparseCooTensor:
    """Per-channel affine norm over the VALUES (active sites only —
    reference sparse BatchNorm normalizes the nnz x C value matrix)."""
    import jax.numpy as jnp

    def _arr(v):
        return v._data if isinstance(v, Tensor) else jnp.asarray(v)

    vals, m, var = x._mat.data, _arr(mean), _arr(variance)

    def fn(w, b):
        return (vals - m) / jnp.sqrt(var + epsilon) * w + b

    y = _apply_params(fn, weight, bias)
    sp = SparseCooTensor(_bcoo().BCOO((y._data, x._mat.indices),
                                      shape=x._mat.shape))
    if not y.stop_gradient:
        sp._values_tensor = y
    return sp


class _SparseConvBase(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__()
        k = _norm3(kernel_size)
        self._stride, self._padding = stride, padding
        self._dilation, self._groups = dilation, groups
        self._data_format = data_format
        # DHWIO — the reference sparse conv weight layout
        self.weight = self.create_parameter(
            list(k) + [in_channels // groups, out_channels], is_bias=False)
        self.bias = None if bias_attr is False else \
            self.create_parameter([out_channels], is_bias=True)


class Conv3D(_SparseConvBase):
    """Reference: incubate/sparse/nn/layer/conv.py Conv3D."""

    def forward(self, x):
        return conv3d(x, self.weight, self.bias, self._stride,
                      self._padding, self._dilation, self._groups,
                      self._data_format)


class SubmConv3D(_SparseConvBase):
    """Reference: incubate/sparse/nn/layer/conv.py SubmConv3D."""

    def forward(self, x):
        return subm_conv3d(x, self.weight, self.bias, self._stride,
                           self._padding, self._dilation, self._groups,
                           self._data_format)


class MaxPool3D(Layer):
    """Reference: incubate/sparse/nn/layer/pooling.py MaxPool3D."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC"):
        super().__init__()
        self._kernel_size = kernel_size
        self._stride = stride
        self._padding = padding
        self._data_format = data_format

    def forward(self, x):
        return max_pool3d(x, self._kernel_size, self._stride,
                          self._padding, self._data_format)


class ReLU(Layer):
    def forward(self, x):
        return relu(x)


class BatchNorm(Layer):
    """Sparse BatchNorm over values (reference
    incubate/sparse/nn/layer/norm.py BatchNorm): running stats are per
    channel, computed over active sites only."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5):
        super().__init__()
        import jax.numpy as jnp
        from ..nn.initializer import Constant
        self._momentum, self._epsilon = momentum, epsilon
        self.weight = self.create_parameter(
            [num_features], is_bias=False, default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_features], is_bias=True)
        self.register_buffer(
            "_mean", Tensor(jnp.zeros([num_features], jnp.float32)))
        self.register_buffer(
            "_variance", Tensor(jnp.ones([num_features], jnp.float32)))

    def forward(self, x: SparseCooTensor):
        import jax.numpy as jnp
        vals = x._mat.data
        if self.training:
            mean = vals.mean(axis=0)
            var = vals.var(axis=0)
            m = self._momentum
            self._buffers["_mean"]._data = (
                m * self._mean._data + (1 - m) * mean).astype(jnp.float32)
            self._buffers["_variance"]._data = (
                m * self._variance._data + (1 - m) * var).astype(
                    jnp.float32)
        else:
            mean, var = self._mean._data, self._variance._data
        return batch_norm(x, mean, var, self.weight, self.bias,
                          self._epsilon)
