"""Build + bind the C inference API (native/tpu_infer_capi.cc).

Reference analog: paddle/fluid/inference/capi_exp/pd_inference_api.h —
the C ABI that lets C/C++/Go/Rust serving processes run a saved model
without the host language's runtime. Here the .so embeds CPython (the
predictor stack is Python-over-PjRt), so a C consumer links
``libtpu_infer_capi`` and calls::

    PDT_Init("/path/to/site-packages-or-repo");
    void* p = PDT_PredictorCreate("/models/resnet50");
    PDT_PredictorRun(p, data, shape, ndim, &out, &out_shape, &out_ndim);

``load_capi()`` JIT-builds the library with this interpreter's embed
flags and returns (ctypes CDLL, path) — the path is what a real C build
would link against.
"""
from __future__ import annotations

import ctypes
import os
import sysconfig

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native",
    "tpu_infer_capi.cc")


def _embed_flags():
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_config_var("VERSION")
    cflags = [f"-I{inc}"]
    ldflags = [f"-L{libdir}", f"-lpython{ver}"] if libdir else \
        [f"-lpython{ver}"]
    return cflags, ldflags


def build_capi_library() -> str:
    """Compile (cached) and return the .so path for C consumers."""
    from ..utils import cpp_extension
    cflags, ldflags = _embed_flags()
    ns = cpp_extension.load("tpu_infer_capi", [_SRC],
                            extra_cxx_cflags=cflags,
                            extra_ldflags=ldflags)
    return ns.__so_path__


def load_capi():
    """(CDLL with typed signatures, library path) for in-process use —
    the test harness's stand-in for a real C caller."""
    path = build_capi_library()
    lib = ctypes.CDLL(path)
    lib.PDT_Init.argtypes = [ctypes.c_char_p]
    lib.PDT_Init.restype = ctypes.c_int
    lib.PDT_PredictorCreate.argtypes = [ctypes.c_char_p]
    lib.PDT_PredictorCreate.restype = ctypes.c_void_p
    lib.PDT_PredictorDestroy.argtypes = [ctypes.c_void_p]
    lib.PDT_PredictorDestroy.restype = None
    lib.PDT_BufferFree.argtypes = [ctypes.c_void_p]
    lib.PDT_BufferFree.restype = None
    lib.PDT_LastError.argtypes = []
    lib.PDT_LastError.restype = ctypes.c_char_p
    lib.PDT_PredictorRun.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
        ctypes.POINTER(ctypes.c_int)]
    lib.PDT_PredictorRun.restype = ctypes.c_int
    return lib, path
