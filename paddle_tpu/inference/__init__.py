"""``paddle.inference`` — the deployment/serving facade (L9).

Reference analog: AnalysisPredictor + AnalysisConfig
(paddle/fluid/inference/api/analysis_predictor.h, paddle_inference_api.h).
TPU-native collapse (SURVEY §7): the reference's analysis passes (IR fusion,
TRT subgraphs, memory reuse) are XLA's job; the predictor is a deserialized
StableHLO artifact executed via PjRt. The AnalysisConfig surface keeps the
reference's ergonomics where meaningful and records-but-ignores GPU/TRT
switches that have no TPU analog.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .. import jit as _jit
from ..framework.tensor import Tensor

__all__ = ["Config", "Predictor", "create_predictor", "PredictorTensor",
           "BatchingEngine"]

from .serving import BatchingEngine  # noqa: E402,F401


class Config:
    """Reference: AnalysisConfig (inference/api/analysis_config.cc)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # paddle convention: prog_file like /p/model.pdmodel
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file
        self._memory_pool_mb = 0
        self._flags: Dict[str, object] = {}

    def set_prog_file(self, p):
        if p and p.endswith(".pdmodel"):
            p = p[: -len(".pdmodel")]
        self._prefix = p

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    # GPU/TRT surface: recorded, inert on TPU (XLA owns these decisions).
    # Accepting them SILENTLY is a usability trap (r4 review weak #6): a
    # user porting a reference deployment would believe TRT kicked in —
    # warn once per knob instead.
    def _inert(self, knob, detail):
        import warnings
        warnings.warn(
            f"inference.Config.{knob} has no effect on the TPU backend "
            f"({detail}); the setting is recorded but ignored",
            UserWarning, stacklevel=3)

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._inert("enable_use_gpu", "execution targets the TPU via XLA")
        self._flags["use_gpu"] = True

    def disable_gpu(self):
        self._flags["use_gpu"] = False

    def enable_tensorrt_engine(self, **kwargs):
        self._inert("enable_tensorrt_engine",
                    "XLA performs the fusion/lowering TRT would")
        self._flags["tensorrt"] = kwargs

    def switch_ir_optim(self, enable=True):
        self._inert("switch_ir_optim", "XLA's pipeline always optimizes")
        self._flags["ir_optim"] = enable

    def enable_memory_optim(self):
        self._inert("enable_memory_optim", "XLA plans buffers itself")
        self._flags["memory_optim"] = True


class PredictorTensor:
    """Zero-copy-style input/output handle (reference ZeroCopyTensor)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.asarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def reshape(self, shape):
        pass  # shapes are static in the exported artifact


class Predictor:
    """Reference: AnalysisPredictor::Run. Wraps a jit.load artifact."""

    def __init__(self, config: Config):
        if not config._prefix:
            raise ValueError("Config needs the model path prefix")
        self._layer = _jit.load(config._prefix)
        self._input_names = self._layer.input_names
        self._inputs: Dict[str, PredictorTensor] = {
            n: PredictorTensor(n) for n in self._input_names}
        self._outputs: List[np.ndarray] = []

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name) -> PredictorTensor:
        return self._inputs[name]

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        if inputs is None:
            unset = [n for n in self._input_names
                     if self._inputs[n]._value is None]
            if unset:
                raise ValueError(
                    f"input(s) {unset} were never set — call "
                    f"get_input_handle(name).copy_from_cpu(arr) first")
            inputs = [self._inputs[n].copy_to_cpu()
                      for n in self._input_names]
        out = self._layer(*inputs)
        flat = out if isinstance(out, (list, tuple)) else [out]
        self._outputs = [o.numpy() if isinstance(o, Tensor) else
                         np.asarray(o) for o in flat]
        return self._outputs

    def get_output_names(self):
        return [f"output_{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name) -> PredictorTensor:
        idx = int(name.split("_")[-1])
        t = PredictorTensor(name)
        t._value = self._outputs[idx]
        return t


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
