"""Batched serving over the Predictor — the deployment hot path.

Reference analog: the AnalysisPredictor serve loop
(paddle/fluid/inference/api/analysis_predictor.cc:1) and its zero-copy
batch handles; production deployments there batch requests server-side
(paddle-serving). TPU-native version: request batching matters MORE on
TPU — per-call host→device dispatch dominates small-batch latency, and
the MXU is idle below ~8 samples — so the engine gathers concurrent
requests into padded buckets (power-of-two batch sizes: one XLA compile
per bucket, not per request count) and splits results back per caller.
"""
from __future__ import annotations

import queue
import threading
from typing import List, Optional

import numpy as np

__all__ = ["BatchingEngine"]


class _Request:
    __slots__ = ("arrays", "event", "result", "error")

    def __init__(self, arrays):
        self.arrays = arrays
        self.event = threading.Event()
        self.result = None
        self.error = None


class BatchingEngine:
    """Gathers concurrent ``infer`` calls into padded batches.

    * ``max_batch_size`` — upper bucket; requests beyond it wait for the
      next cycle.
    * ``max_delay_ms`` — how long the gatherer waits for co-riders after
      the first request lands. 0 serves singles immediately (latency
      mode).
    * batch sizes are rounded UP to powers of two and padded by repeating
      the last sample, so the artifact compiles once per bucket; padding
      rows are dropped before returning.

    Thread-safe; callers block in ``infer`` until their rows return.
    """

    def __init__(self, predictor, max_batch_size: int = 32,
                 max_delay_ms: float = 2.0):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        self._predictor = predictor
        self._max_batch = int(max_batch_size)
        self._delay = max(0.0, float(max_delay_ms)) / 1000.0
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._closed = False
        self._close_lock = threading.Lock()
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    # -- client side -------------------------------------------------------
    def infer(self, *arrays) -> List[np.ndarray]:
        """One logical request: each array's leading dim is this caller's
        batch (usually 1). Blocks until results are ready."""
        req = _Request([np.asarray(a) for a in arrays])
        # the lock makes enqueue atomic with close(): a request can never
        # slip in after the close sentinel and hang in event.wait()
        with self._close_lock:
            if self._closed:
                raise RuntimeError("BatchingEngine is closed")
            self._queue.put(req)
        req.event.wait()
        if req.error is not None:
            raise req.error
        return req.result

    def close(self, timeout: Optional[float] = None):
        """Stop accepting work and DRAIN: the shutdown sentinel queues
        BEHIND everything already submitted, so the worker serves every
        in-flight request before exiting — close() is a graceful drain,
        not an abandonment. Pass ``timeout`` (seconds) to bound the
        wait; requests still pending past it (or left behind by a dead
        worker) fail with a "closed" error instead of hanging their
        callers forever."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
            self._queue.put(None)       # wake the worker
        self._worker.join(timeout)
        # after an untimed join the queue holds nothing; with a timeout
        # (or a dead worker) fail the leftovers so no caller hangs
        while True:
            try:
                r = self._queue.get_nowait()
            except queue.Empty:
                break
            if r is None:
                if self._worker.is_alive():
                    # a slow batch outlived the join timeout: the worker
                    # still needs its shutdown sentinel — put it back
                    self._queue.put(None)
                    break
                continue
            if not r.event.is_set():
                r.error = RuntimeError("BatchingEngine is closed")
                r.event.set()

    # -- worker side -------------------------------------------------------
    def _gather(self) -> Optional[List[_Request]]:
        first = self._queue.get()
        while first is not None and not self._valid(first):
            first = self._queue.get()      # malformed: already failed
        if first is None:
            return None
        batch = [first]
        rows = first.arrays[0].shape[0]
        import time
        deadline = time.perf_counter() + self._delay
        while rows < self._max_batch:
            remaining = deadline - time.perf_counter()
            if remaining <= 0 and self._delay > 0:
                break
            try:
                nxt = self._queue.get(
                    timeout=max(remaining, 0) if self._delay > 0 else None
                ) if self._delay > 0 else self._queue.get_nowait()
            except queue.Empty:
                break
            if nxt is None:
                self._queue.put(None)   # re-post the close sentinel
                break
            if not self._valid(nxt):
                continue
            batch.append(nxt)
            rows += nxt.arrays[0].shape[0]
        return batch

    @staticmethod
    def _valid(req) -> bool:
        """Fail malformed requests HERE instead of letting them raise in
        the gather loop and kill the worker thread (which would hang
        every subsequent caller forever)."""
        if req.arrays and all(getattr(a, "ndim", 0) >= 1
                              for a in req.arrays):
            return True
        req.error = ValueError(
            "infer() needs at least one array, each with a leading "
            "batch dimension")
        req.event.set()
        return False

    @staticmethod
    def _bucket(n: int, cap: int) -> int:
        """Next power of two >= n — ALWAYS a pow2, even above
        max_batch_size, so oversize client batches land in O(log n)
        compile buckets instead of one XLA compile per distinct row
        count."""
        b = 1
        while b < n:
            b *= 2
        return b

    def _serve(self, batch: List[_Request]) -> None:
        """Pad one gathered batch to its pow2 bucket, run the predictor,
        split the rows back per caller."""
        n_inputs = len(batch[0].arrays)
        rows = [r.arrays[0].shape[0] for r in batch]
        total = sum(rows)
        padded = self._bucket(total, self._max_batch)
        feeds = []
        for j in range(n_inputs):
            stacked = np.concatenate([r.arrays[j] for r in batch])
            if padded > total:
                pad = np.repeat(stacked[-1:], padded - total, axis=0)
                stacked = np.concatenate([stacked, pad])
            feeds.append(stacked)
        outs = self._predictor.run(feeds)
        start = 0
        for r, n in zip(batch, rows):
            r.result = [o[start:start + n] for o in outs]
            start += n
            r.event.set()

    def _loop(self):
        while True:
            batch = self._gather()
            if batch is None:
                return
            try:
                self._serve(batch)
            except Exception as batch_exc:              # noqa: BLE001
                if len(batch) == 1:
                    batch[0].error = batch_exc
                    batch[0].event.set()
                    continue
                # one poisoned request must not fail its co-riders:
                # retry each request as its own batch — the healthy ones
                # succeed, only the poisoned one propagates its error
                for r in batch:
                    if r.event.is_set():
                        continue
                    try:
                        self._serve([r])
                    except Exception as e:              # noqa: BLE001
                        r.error = e
                        r.event.set()
