"""Elastic training: membership, heartbeats, fault detection, rebuild.

Reference: python/paddle/distributed/fleet/elastic/manager.py:131 — node
registry + heartbeats in ETCD, scale up/down by rewriting
DISTRIBUTED_TRAINER_ENDPOINTS and restarting, exit-code-101 restart
signalling.

TPU-native shape: the registry is a tiny stdlib-TCP master (newline-JSON
request/response, threaded) hosted by the rank-0 LAUNCHER — the ETCD role
without the external dependency (single-master fate-sharing is the
documented trade-off). Launch agents register their node, heartbeat on a
thread, and poll membership; when a node's heartbeats lapse (dead host) or
a node joins, the membership VERSION bumps and every launcher rebuilds its
local pod against the new node list: ranks reassigned by sorted node
order, world size rewritten, and a fresh PjRt coordination port per
version so the re-rendezvous never collides with a stale service.
Workers resume from their latest checkpoint — jax's coordination service
replaces the TCPStore, sharded checkpoints (distributed/checkpoint.py)
replace the reference's per-rank state files.
"""
from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["ElasticMaster", "ElasticAgent", "sort_nodes"]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def sort_nodes(nodes) -> List[str]:
    """Rank order for a membership list: numeric node_rank suffix first,
    then name — so the master-hosting node (node_rank 0) always gets
    global rank 0 and the PjRt coordinator binds on its own host."""
    def key(n: str):
        name, _, suffix = n.rpartition("#")
        try:
            return (0, int(suffix), name)
        except ValueError:
            return (1, 0, n)
    return sorted(nodes, key=key)


class ElasticMaster:
    """Membership registry + TTL sweeper (the ETCD analog).

    Protocol: one JSON line request -> one JSON line response per
    connection. Commands: register / heartbeat / leave / status.
    """

    def __init__(self, port: int, ttl: float = 6.0,
                 sweep_interval: float = 0.5):
        self.ttl = float(ttl)
        self._lock = threading.Lock()
        self._nodes: Dict[str, float] = {}   # node_id -> last heartbeat
        self._version = 0
        self._pjrt_port = _free_port()
        master = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    line = self.rfile.readline()
                    req = json.loads(line.decode())
                    resp = master._handle(req)
                except Exception as e:  # malformed request
                    resp = {"ok": 0, "error": str(e)}
                self.wfile.write((json.dumps(resp) + "\n").encode())

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server(("0.0.0.0", port), Handler)
        self.port = self._server.server_address[1]
        self._threads = [
            threading.Thread(target=self._server.serve_forever,
                             daemon=True),
            threading.Thread(target=self._sweep_loop,
                             args=(sweep_interval,), daemon=True),
        ]
        self._stopped = False
        for t in self._threads:
            t.start()

    # -- state transitions -------------------------------------------------
    def _bump(self):
        self._version += 1
        self._pjrt_port = _free_port()  # fresh rendezvous per membership

    def _handle(self, req: dict) -> dict:
        cmd = req.get("cmd")
        node = req.get("node")
        with self._lock:
            if cmd == "register":
                if node not in self._nodes:
                    self._bump()
                self._nodes[node] = time.time()
            elif cmd == "heartbeat":
                if node in self._nodes:
                    self._nodes[node] = time.time()
                else:
                    # expired while away: re-register (scale back up)
                    self._bump()
                    self._nodes[node] = time.time()
            elif cmd == "leave":
                if node in self._nodes:
                    del self._nodes[node]
                    self._bump()
            elif cmd != "status":
                return {"ok": 0, "error": f"unknown cmd {cmd!r}"}
            return {"ok": 1, "version": self._version,
                    "nodes": sorted(self._nodes),
                    "pjrt_port": self._pjrt_port}

    def _sweep_loop(self, interval: float):
        while not self._stopped:
            time.sleep(interval)
            now = time.time()
            with self._lock:
                dead = [n for n, last in self._nodes.items()
                        if now - last > self.ttl]
                for n in dead:
                    del self._nodes[n]
                if dead:
                    self._bump()

    def shutdown(self):
        self._stopped = True
        self._server.shutdown()
        self._server.server_close()


class ElasticAgent:
    """Launcher-side client: register + background heartbeats + membership
    polls (reference: the elastic manager inside each launch controller).
    """

    def __init__(self, master_addr: str, node_id: str,
                 heartbeat_interval: float = 1.0, timeout: float = 5.0):
        host, port = master_addr.rsplit(":", 1)
        self._addr: Tuple[str, int] = (host, int(port))
        self.node_id = node_id
        self._interval = heartbeat_interval
        self._timeout = timeout
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- rpc ---------------------------------------------------------------
    def _call(self, cmd: str) -> dict:
        with socket.create_connection(self._addr,
                                      timeout=self._timeout) as s:
            s.sendall((json.dumps(
                {"cmd": cmd, "node": self.node_id}) + "\n").encode())
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = s.recv(4096)
                if not chunk:
                    break
                buf += chunk
        if not buf:
            # master died between accept and reply: surface as the same
            # class callers already guard against
            raise ConnectionError("empty reply from elastic master")
        return json.loads(buf.decode())

    def register(self, retries: int = 50, delay: float = 0.2) -> dict:
        last: Exception = RuntimeError("unreached")
        for _ in range(retries):
            try:
                return self._call("register")
            except OSError as e:
                last = e
                time.sleep(delay)
        raise RuntimeError(
            f"cannot reach elastic master at {self._addr}: {last}")

    def status(self) -> dict:
        return self._call("status")

    def leave(self) -> None:
        try:
            self._call("leave")
        except OSError:
            pass  # master already gone

    # -- heartbeat thread --------------------------------------------------
    def start_heartbeat(self):
        def loop():
            while not self._stop.wait(self._interval):
                try:
                    self._call("heartbeat")
                except (OSError, ValueError):
                    pass  # master unreachable/garbled: TTL will expire us
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop_heartbeat(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
