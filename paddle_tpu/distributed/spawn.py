"""``paddle.distributed.spawn`` (reference: python/paddle/distributed/
spawn.py) — in-code multi-process launch as an alternative to
``python -m paddle_tpu.distributed.launch``.

Spawns ``nprocs`` fresh python processes (spawn context: fork is unsafe
after jax initializes its thread pools), wiring the same PADDLE_* /
coordination-service env the launcher sets, and runs ``func(*args)`` in
each. ``func`` must be importable (module-level) for pickling.
"""
from __future__ import annotations

import multiprocessing as mp
import os
from typing import Optional

from .launch import _free_port

__all__ = ["spawn", "ParallelEnv"]


class ParallelEnv:
    """Reference: fluid/dygraph/parallel.py ParallelEnv — the per-process
    view of the distributed environment (rank, world size, endpoints)."""

    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        dev = os.environ.get("FLAGS_selected_tpus",
                             os.environ.get("FLAGS_selected_gpus", "0"))
        # reference ParallelEnv: a comma list selects this process's first
        self._device_id = int(str(dev).split(",")[0])
        self._current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT",
                                                "")
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._trainer_endpoints = eps.split(",") if eps else []

    @property
    def rank(self):
        return self._rank

    local_rank = rank

    @property
    def world_size(self):
        return self._world_size

    nranks = world_size

    @property
    def device_id(self):
        return self._device_id

    dev_id = device_id

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints


def _worker(func, args):
    func(*args)


def spawn(func, args=(), nprocs: Optional[int] = None, join: bool = True,
          daemon: bool = False, backend: Optional[str] = None, **options):
    """Launch ``func(*args)`` in ``nprocs`` fresh processes with PADDLE_*
    env wired; returns the context (list of processes) when ``join=False``.
    """
    if nprocs is None:
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    coordinator = options.get(
        "master", f"127.0.0.1:{_free_port()}")
    endpoints = ",".join(
        f"127.0.0.1:{_free_port()}" for _ in range(nprocs))
    ctx = mp.get_context("spawn")
    procs = []
    # env is set in the PARENT around each start(): spawn children inherit
    # it before unpickling, so modules that initialize jax at import time
    # (the normal `import paddle_tpu` pattern) see the right platform and
    # rank — setting env inside the worker would be too late
    saved = {k: os.environ.get(k) for k in
             ("PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM", "PADDLE_MASTER",
              "PADDLE_CURRENT_ENDPOINT", "PADDLE_TRAINER_ENDPOINTS",
              "FLAGS_selected_tpus", "JAX_PLATFORMS",
              "PALLAS_AXON_POOL_IPS")}
    try:
        for rank in range(nprocs):
            os.environ["PADDLE_TRAINER_ID"] = str(rank)
            os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
            os.environ["PADDLE_MASTER"] = coordinator
            os.environ["PADDLE_CURRENT_ENDPOINT"] = \
                endpoints.split(",")[rank]
            os.environ["PADDLE_TRAINER_ENDPOINTS"] = endpoints
            os.environ["FLAGS_selected_tpus"] = str(rank)
            if backend == "cpu" or \
                    os.environ.get("PADDLE_SPAWN_CPU") == "1":
                os.environ["JAX_PLATFORMS"] = "cpu"
                os.environ["PALLAS_AXON_POOL_IPS"] = ""
            p = ctx.Process(target=_worker, args=(func, args),
                            daemon=daemon)
            p.start()
            procs.append(p)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    if not join:
        return procs
    failed = []
    for rank, p in enumerate(procs):
        p.join()
        if p.exitcode != 0:
            failed.append((rank, p.exitcode))
    if failed:
        raise RuntimeError(f"spawn: ranks failed: {failed}")
    return procs
