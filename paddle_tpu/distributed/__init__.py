"""``paddle.distributed`` — distributed training API.

Analog of the reference's ``python/paddle/distributed/``: collective ops,
environment bootstrap, fleet facade, parallelized layers. See
``SURVEY.md`` §2.4 for the strategy inventory this package re-implements
TPU-first (XLA collectives over a hybrid Mesh instead of NCCL rings).
"""
from . import env  # noqa: F401
from .collective import (  # noqa: F401
    ReduceOp, all_gather, all_gather_object, all_reduce, all_to_all,
    alltoall, barrier, broadcast, destroy_process_group, get_backend,
    get_group, irecv, is_available, isend, new_group, recv, reduce,
    reduce_scatter, scatter, send, wait,
)
from .env import (  # noqa: F401
    get_rank, get_world_size, init_parallel_env, is_initialized,
)
from .spawn import ParallelEnv, spawn  # noqa: F401
from . import fleet  # noqa: F401
from . import spmd  # noqa: F401
from .fleet.meta_parallel.parallel_layers.mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)

from .ps_compat import (  # noqa: F401
    CountFilterEntry, InMemoryDataset, ParallelMode, ProbabilityEntry,
    QueueDataset, ShowClickEntry, gloo_barrier, gloo_init_parallel_env,
    gloo_release, split,
)
from . import embedding  # noqa: F401

__all__ = [
    "ReduceOp", "all_gather", "all_reduce", "alltoall", "barrier",
    "broadcast", "get_group", "new_group", "recv", "reduce", "scatter",
    "send", "get_rank", "get_world_size", "init_parallel_env",
    "is_initialized", "fleet", "spmd", "split", "ParallelMode",
    "gloo_init_parallel_env", "gloo_barrier", "gloo_release",
    "InMemoryDataset", "QueueDataset", "CountFilterEntry",
    "ProbabilityEntry", "ShowClickEntry", "embedding",
]
