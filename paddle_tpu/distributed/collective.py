"""Collective communication API.

Analog of the reference's ``python/paddle/distributed/collective.py``
(broadcast/all_reduce/reduce/all_gather/scatter/alltoall/send/recv over
ProcessGroupNCCL / c_* ops, :343-1040).

TPU-native design: there are two call sites with different mechanics —

* **Inside a sharded program** (shard_map over a mesh axis): collectives are
  ``jax.lax`` ops (psum/all_gather/ppermute/all_to_all) — this module's
  ``*_in_axis`` functions. XLA schedules them on ICI/DCN; there is no
  process-group object because the mesh axis IS the group.
* **Eager, process-level** (API parity with the reference): operates on a
  Tensor replicated/sharded across the registered mesh. Single-process
  single-device degenerates to identity, which keeps the reference's
  1-GPU semantics.

``new_group`` returns a lightweight Group naming a mesh axis, which the
meta-parallel layers use to pick their PartitionSpec axis.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..framework.monitor import stat_add
from ..framework.tensor import Tensor
from ..profiler import span as _prof
from . import env

__all__ = ["ReduceOp", "Group", "new_group", "get_group", "all_reduce",
           "all_gather", "broadcast", "reduce", "scatter", "alltoall",
           "all_to_all", "reduce_scatter", "send", "recv", "isend", "irecv",
           "wait", "barrier", "get_backend", "is_available",
           "destroy_process_group", "all_gather_object", "psum_in_axis",
           "all_gather_in_axis", "ppermute_in_axis", "all_to_all_in_axis",
           "reduce_scatter_in_axis", "observe_collective_time",
           "timing_sampled", "note_step_exchange",
           "communication_report", "communication_report_table"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A named communication group == a mesh axis (+ optional rank subset).

    The reference's Group carries NCCL ring state; here it only names the
    mesh axis collectives run over.
    """

    def __init__(self, gid: int, axis_name: Optional[str] = None,
                 ranks: Optional[List[int]] = None):
        self.id = gid
        self.axis_name = axis_name
        self.ranks = ranks or []
        self.nranks = len(self.ranks) if self.ranks else \
            (dict(zip(env.get_mesh().axis_names, env.get_mesh().devices.shape))
             [axis_name] if (env.get_mesh() is not None and axis_name) else 1)

    def __repr__(self):
        return f"Group(id={self.id}, axis={self.axis_name}, " \
               f"nranks={self.nranks})"


_groups = {}
_next_gid = [1]
_default_group = Group(0, None, [])


def new_group(ranks=None, backend=None, axis_name=None, timeout=None):
    gid = _next_gid[0]
    _next_gid[0] += 1
    g = Group(gid, axis_name, list(ranks) if ranks else None)
    _groups[gid] = g
    return g


def get_group(gid=0):
    return _groups.get(gid, _default_group)


# ---------------------------------------------------------------------------
# in-axis collectives (for use inside shard_map'd code)
# ---------------------------------------------------------------------------

def psum_in_axis(x, axis_name: str):
    import jax
    with _traced("psum_in_axis", x):
        return jax.lax.psum(x, axis_name)


def all_gather_in_axis(x, axis_name: str, tiled=True, axis=0):
    import jax
    with _traced("all_gather_in_axis", x):
        return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ppermute_in_axis(x, axis_name: str, perm):
    import jax
    with _traced("ppermute_in_axis", x):
        return jax.lax.ppermute(x, axis_name, perm)


def all_to_all_in_axis(x, axis_name: str, split_axis=0, concat_axis=0):
    import jax
    with _traced("all_to_all_in_axis", x):
        return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)


def reduce_scatter_in_axis(x, axis_name: str, scatter_axis=0):
    import jax
    with _traced("reduce_scatter_in_axis", x):
        return jax.lax.psum_scatter(x, axis_name,
                                    scatter_dimension=scatter_axis,
                                    tiled=True)


# ---------------------------------------------------------------------------
# observability: every EAGER collective that executes is counted
# (collective_count/<kind> + collective_bytes, monitor counters) and,
# under an active profiler.profile() session, recorded as a span carrying
# its byte count — per-call telemetry like the reference's NCCL event
# hooks. The *_in_axis helpers run INSIDE jit traces, so their counters
# and spans fire once per TRACE (compile), not per device execution —
# they answer "which collectives does this program contain and how big",
# not "how many ran"; steady-state device-side timing comes from the
# XPlane trace (profiler/xplane.py).
# ---------------------------------------------------------------------------

def _payload_bytes(*tensors) -> int:
    n = 0
    for t in tensors:
        data = getattr(t, "_data", t)
        try:
            n += int(data.nbytes)
        except Exception:
            try:  # tracers/avals: size * itemsize
                n += int(np.prod(data.shape)) * data.dtype.itemsize
            except Exception:
                pass
    return n


import contextlib as _contextlib


@_contextlib.contextmanager
def _traced(kind: str, *tensors):
    n = _payload_bytes(*tensors)
    with _prof.record(f"collective/{kind}", "collective",
                      args={"bytes": n}):
        yield
    # reached only when the body did NOT raise: a failed collective must
    # not inflate the telemetry
    stat_add(f"collective_count/{kind}")
    if n:
        stat_add("collective_bytes", n)
        # per-kind bytes: lets a caller prove a SPECIFIC exchange got
        # cheaper (the ZeRO int8 gradient path moves the reduce-scatter
        # payload onto all_to_all at 1/4 the bytes while the param
        # all-gather stays f32 — only per-kind counters can show that)
        stat_add(f"collective_bytes/{kind}", n)


# ---------------------------------------------------------------------------
# device timing (ISSUE 13): the byte counters above price what we SEND;
# collective_time_ms/<kind> prices what it COSTS. Two mechanics:
#
# * eager collectives — a sampled block-until-ready bracket around the
#   call (``_timed_eager``): the first call per kind is always timed,
#   then every FLAGS_collective_timing_every-th, because a per-call
#   device barrier would serialize exactly the pipeline the eager API
#   exists to feed;
# * in-step collectives (the ZeRO exchange) — XLA fuses them inside one
#   donated program where no host timer can see them, so
#   ``hapi/zero.time_step_collectives`` runs each kind ISOLATED in a
#   tiny jitted shard_map over the same mesh axis and payload shape,
#   warmed once (compile excluded) and bracketed here via
#   :func:`observe_collective_time`. What that yields is the EXPOSED
#   (un-overlapped) cost of the exchange — which is the honest number:
#   the current zero step brackets the exchange serially, and the
#   overlap follow-on (ROADMAP) is claimable exactly to the extent this
#   figure shrinks out of the step wall time.
#
# ``collective_bw_gbps/<kind>`` joins the two: payload bytes / measured
# ms, the achieved-bandwidth figure a hardware round compares against
# ICI peak. ``communication_report()`` assembles the whole picture.
# ---------------------------------------------------------------------------

import threading as _threading  # noqa: E402
import time as _time  # noqa: E402

_timing_lock = _threading.Lock()
_timing_counts: dict = {}


def _timing_flag(name: str, default):
    try:
        from ..framework.flags import flag_value
        return flag_value(name)
    except Exception:                                    # noqa: BLE001
        return default


def timing_sampled(kind: str) -> bool:
    """Should THIS call of ``kind`` be device-timed? First call per
    kind: yes; then every FLAGS_collective_timing_every-th. False
    everywhere when FLAGS_collective_timing is off."""
    if not _timing_flag("FLAGS_collective_timing", True):
        return False
    every = max(1, int(_timing_flag("FLAGS_collective_timing_every", 16)))
    with _timing_lock:
        n = _timing_counts.get(kind, 0)
        _timing_counts[kind] = n + 1
    return n % every == 0


# the kinds that make up the CURRENT training step's exchange, noted by
# the ZeRO probe (fp32: reduce_scatter+all_gather; int8: the all_to_all
# pair + all_gather). exposed_ms_per_step sums ONLY these — a one-shot
# broadcast at init, an eager metric all_reduce, or the probe's
# comparison kinds would otherwise be billed as per-step cost and
# overstate the overlap headroom.
_step_exchange_kinds: Optional[tuple] = None


def note_step_exchange(kinds) -> None:
    """Record which collective kinds constitute the live train step's
    exchange (see :func:`communication_report`)."""
    global _step_exchange_kinds
    _step_exchange_kinds = tuple(kinds) if kinds else None


def observe_collective_time(kind: str, ms: float, nbytes: int = 0) -> None:
    """Record one device-timing sample for a collective kind:
    ``collective_time_ms/<kind>`` and, when the payload is known,
    ``collective_bw_gbps/<kind>`` (payload bytes / measured wall)."""
    from ..framework.monitor import stat_observe
    stat_observe(f"collective_time_ms/{kind}", float(ms))
    if nbytes and ms > 0:
        # bytes / (ms * 1e-3 s) / 1e9 B/GB == nbytes / (ms * 1e6)
        stat_observe(f"collective_bw_gbps/{kind}", nbytes / (ms * 1e6))


class _TimingBox:
    """Carries the eager collective's result out of the ``with`` body so
    the sampled bracket can block on the actual device value."""
    __slots__ = ("result",)

    def __init__(self):
        self.result = None


@_contextlib.contextmanager
def _timed_eager(kind: str, *tensors):
    """_traced plus the sampled block-until-ready bracket. The body
    stores its device result in the yielded box; an unsampled call pays
    one lock-free counter read and nothing else."""
    n = _payload_bytes(*tensors)
    sampled = timing_sampled(kind)
    t0 = _time.perf_counter() if sampled else 0.0
    box = _TimingBox()
    with _traced(kind, *tensors):
        yield box
    if sampled and box.result is not None:
        try:
            import jax
            jax.block_until_ready(box.result)
        except Exception:                                # noqa: BLE001
            pass        # a host-only degenerate result has nothing to wait on
        observe_collective_time(
            kind, (_time.perf_counter() - t0) * 1e3, n)


def communication_report() -> dict:
    """The exposed-vs-overlapped communication picture, joined from the
    three per-kind surfaces: byte counters (PR 10), device-timing
    histograms and achieved bandwidth (this PR). Per kind:
    ``{count, bytes_total, time_ms: {p50,...}, achieved_gbps}``; and
    when a training step is live, ``exposed_ms_per_step`` (sum of
    per-kind p50 isolated times) against ``step_p50_ms``
    (``hapi/step_time_ms``) — the fraction of the step the exchange
    would stop costing if fully overlapped (the claim the ZeRO overlap
    follow-on must cash; "Automatic Cross-Replica Sharding", PAPERS.md).
    The collective-pairing analysis pass proves the program CONTAINS a
    matched reduce-scatter/all-gather pair; this report prices it."""
    from ..framework import monitor
    stats = monitor.all_stats()
    hists = monitor.all_histograms()
    kinds = set()
    for k in list(stats) + list(hists):
        for fam in ("collective_bytes/", "collective_count/",
                    "collective_time_ms/", "collective_bw_gbps/"):
            if k.startswith(fam):
                kinds.add(k[len(fam):])
    per_kind = {}
    for kind in sorted(kinds):
        bw = hists.get(f"collective_bw_gbps/{kind}")
        per_kind[kind] = {
            "count": stats.get(f"collective_count/{kind}", 0.0),
            "bytes_total": stats.get(f"collective_bytes/{kind}"),
            "time_ms": hists.get(f"collective_time_ms/{kind}"),
            "achieved_gbps": bw["p50"] if bw else None,
        }
    step = hists.get("hapi/step_time_ms")
    # exposed cost = the step's own exchange (note_step_exchange), so a
    # one-shot broadcast or the probe's comparison kinds never inflate
    # it; with nothing noted (eager-only programs) every timed kind
    # counts — the pre-probe behavior, documented imprecision included
    timed = []
    if _step_exchange_kinds:
        timed = [per_kind[k]["time_ms"]["p50"]
                 for k in _step_exchange_kinds
                 if k in per_kind and per_kind[k]["time_ms"]]
    if not timed:
        timed = [r["time_ms"]["p50"] for r in per_kind.values()
                 if r["time_ms"]]
    exposed = float(sum(timed)) if timed else None
    out = {"per_kind": per_kind,
           "step_p50_ms": step["p50"] if step else None,
           "exposed_ms_per_step": exposed,
           "exposed_fraction": None,
           "overlap_headroom_pct": None}
    if exposed is not None and step and step["p50"] > 0:
        frac = min(1.0, exposed / step["p50"])
        out["exposed_fraction"] = frac
        out["overlap_headroom_pct"] = 100.0 * frac
    return out


def communication_report_table() -> str:
    """Human-readable :func:`communication_report` (statusz section)."""
    rep = communication_report()
    if not rep["per_kind"]:
        return "(no collectives recorded)"
    lines = [f"{'kind':<24} {'count':>8} {'bytes':>14} "
             f"{'p50 ms':>9} {'GB/s':>7}"]
    for kind, row in sorted(rep["per_kind"].items()):
        t = row.get("time_ms") or {}
        lines.append(
            f"{kind:<24} {row.get('count', 0):>8.0f} "
            f"{row.get('bytes_total') or 0:>14.0f} "
            f"{t.get('p50', 0.0):>9.3f} "
            f"{row.get('achieved_gbps') or 0:>7.2f}")
    if rep["exposed_ms_per_step"] is not None:
        lines.append(
            f"exposed comm/step {rep['exposed_ms_per_step']:.3f} ms"
            + (f" of step p50 {rep['step_p50_ms']:.3f} ms "
               f"({rep['overlap_headroom_pct']:.1f}% overlap headroom)"
               if rep["step_p50_ms"] else ""))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# eager process-level API (reference parity)
# ---------------------------------------------------------------------------

def _degenerate() -> bool:
    """True when there is no multi-device mesh to communicate over."""
    mesh = env.get_mesh()
    return mesh is None or int(np.prod(mesh.devices.shape)) <= 1


def _axis_of(group) -> str:
    mesh = env.get_mesh()
    if group is not None and getattr(group, "axis_name", None):
        return group.axis_name
    # default: reduce over every mesh axis
    return mesh.axis_names


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """Eager all-reduce across the mesh (identity when single device).

    Under SPMD the data-parallel grad sync happens inside the jitted step;
    this eager entry point exists for reference API parity (e.g. manual
    metric reduction)."""
    with _timed_eager("all_reduce", tensor) as _t:
        if _degenerate():
            _t.result = tensor._data   # identity, but the bracket works
            return tensor
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = env.get_mesh()
        axes = _axis_of(group)
        axes = (axes,) if isinstance(axes, str) else tuple(axes)

        def f(x):
            red = {"sum": jax.lax.psum, "max": jax.lax.pmax,
                   "min": jax.lax.pmin}[op if op != ReduceOp.AVG else "sum"]
            y = red(x, axes)
            if op == ReduceOp.AVG:
                y = y / np.prod([mesh.shape[a] for a in axes])
            return y

        spec = P(axes if len(axes) > 1 else axes[0])
        out = jax.jit(
            jax.shard_map(f, mesh=mesh, in_specs=spec, out_specs=spec))(
            _sharded_like(tensor._data, mesh, spec))
        tensor._data = out
        _t.result = out
        return tensor


def _sharded_like(arr, mesh, spec):
    import jax
    from jax.sharding import NamedSharding
    return jax.device_put(arr, NamedSharding(mesh, spec))


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    if _degenerate():
        # counters/spans only on the path that executes — a call that
        # raises NotImplementedError must not inflate the telemetry
        with _traced("all_gather", tensor):
            tensor_list.append(Tensor(tensor._data))
            return tensor_list
    raise NotImplementedError(
        "eager all_gather over a live mesh: express the gather inside the "
        "jitted step (all_gather_in_axis) — eager loops over mesh shards "
        "are not a TPU execution model")


def broadcast(tensor, src=0, group=None, sync_op=True):
    with _timed_eager("broadcast", tensor) as _t:
        if _degenerate():
            _t.result = tensor._data
            return tensor
        # replicated arrays are already consistent; broadcast is the act
        # of resharding to full replication
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        tensor._data = jax.device_put(
            tensor._data, NamedSharding(env.get_mesh(), P()))
        _t.result = tensor._data
        return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if _degenerate():
        if tensor_list:
            tensor._data = tensor_list[0]._data
        return tensor
    raise NotImplementedError(
        "eager scatter over a live mesh: use sharding annotations "
        "(device_put with a PartitionSpec) instead")


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    if _degenerate():
        with _traced("alltoall", *in_tensor_list):
            outs = [Tensor(t._data) for t in in_tensor_list]
            if out_tensor_list is not None:
                out_tensor_list.extend(outs)
                return out_tensor_list
            return outs
    raise NotImplementedError(
        "eager alltoall over a live mesh: use all_to_all_in_axis inside "
        "the jitted step (see MoELayer)")


def send(tensor, dst=0, group=None, sync_op=True):
    if _degenerate():
        return tensor
    raise NotImplementedError(
        "point-to-point send is expressed as ppermute inside the pipeline "
        "schedule on TPU (see PipelineLayer)")


def recv(tensor, src=0, group=None, sync_op=True):
    if _degenerate():
        return tensor
    raise NotImplementedError(
        "point-to-point recv is expressed as ppermute inside the pipeline "
        "schedule on TPU (see PipelineLayer)")


def barrier(group=None):
    """Host-level barrier: forces completion of all outstanding work."""
    import jax
    with _traced("barrier"):
        arr = jax.numpy.zeros(())
        jax.block_until_ready(arr)
    if env.get_world_size() > 1:
        # cross-host rendezvous via a tiny global psum
        from jax.sharding import PartitionSpec as P
        mesh = env.get_mesh()
        if mesh is not None:
            all_reduce(Tensor(arr))


def all_to_all(in_tensor_list, out_tensor_list=None, group=None,
               sync_op=True):
    """Reference name for alltoall (python/paddle/distributed/collective.py
    exposes both)."""
    return alltoall(in_tensor_list, out_tensor_list, group, sync_op)


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """Sum tensor_list across ranks, scatter one shard per rank. Eager
    entry point; inside jitted steps this is lax.psum_scatter riding ICI
    (reduce_scatter_in_axis)."""
    if _degenerate():
        with _traced("reduce_scatter", *tensor_list):
            summed = tensor_list[0]
            for t in tensor_list[1:]:
                summed = summed + t
            tensor._data = summed._data if hasattr(summed, "_data") \
                else summed
            return tensor
    raise NotImplementedError(
        "multi-rank eager reduce_scatter: use reduce_scatter_in_axis inside "
        "shard_map (the SPMD engine emits it for ZeRO grads)")


class _CompletedTask:
    def wait(self):
        return True

    def is_completed(self):
        return True


def isend(tensor, dst=0, group=None):
    send(tensor, dst, group, sync_op=False)
    return _CompletedTask()


def irecv(tensor, src=0, group=None):
    recv(tensor, src, group, sync_op=False)
    return _CompletedTask()


def wait(tensor, group=None, use_calc_stream=True):
    """Stream-ordering wait (reference: c_wait_compute/c_wait_comm). XLA
    orders collectives by data dependence; this blocks the host on the
    value for the eager path."""
    import jax
    if hasattr(tensor, "_data"):
        jax.block_until_ready(tensor._data)
    return _CompletedTask()


def get_backend(group=None) -> str:
    """The one TPU backend: XLA collectives over ICI/DCN."""
    return "XLA"


def is_available() -> bool:
    return True


def destroy_process_group(group=None):
    if group is None and env.is_initialized():
        import jax
        jax.distributed.shutdown()
        env.reset()


def all_gather_object(object_list, obj, group=None):
    """Gather arbitrary picklable objects (reference contract). Single
    process: identity; multi-host uses the coordination-service KV store."""
    ws = env.get_world_size()
    if ws <= 1:
        object_list.append(obj)
        return
    raise NotImplementedError(
        "cross-host object gather is served by the launcher's KV store; "
        "gather arrays with all_gather instead")
