"""``paddle.distributed.auto_parallel`` — semi-automatic SPMD annotations.

Reference: auto_parallel/process_mesh.py:39 (ProcessMesh),
interface.py:34/73 (shard_tensor / shard_op), engine.py (high-level fit),
completion.py / partitioner.py / reshard.py (the 21k-LoC propagation +
program-rewrite machinery).

TPU-native: annotations map 1:1 onto GSPMD — ``shard_tensor`` is a
``with_sharding_constraint`` (traced) or sharded ``device_put`` (eager),
and the entire Completer/Partitioner/Resharder pipeline collapses into
XLA's SPMD propagation pass: annotate a few tensors, XLA completes the
rest and inserts the collectives the reference's Resharder emits by hand.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from ...framework.tensor import Tensor
from .. import env as _env

__all__ = ["ProcessMesh", "shard_tensor", "shard_op", "get_mesh",
           "set_mesh"]

_current = {"mesh": None}


class ProcessMesh:
    """Logical mesh of processes/devices (reference process_mesh.py:39).

    ``mesh``: nested list / ndarray of device (process) ids giving the
    topology; ``dim_names``: one name per mesh dimension. The physical
    jax ``Mesh`` places device i of ``jax.devices()`` at logical id i.
    """

    def __init__(self, mesh: Union[Sequence, np.ndarray],
                 dim_names: Optional[List[str]] = None,
                 process_ids=None):
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise ValueError(
                f"{len(dim_names)} dim_names for a {arr.ndim}-d mesh")
        self._ids = arr
        self._dim_names = list(dim_names)

    @property
    def shape(self):
        return list(self._ids.shape)

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return self._ids.reshape(-1).tolist()

    @property
    def ndim(self):
        return self._ids.ndim

    def get_dim_size(self, name: str) -> int:
        return self._ids.shape[self._dim_names.index(name)]

    def jax_mesh(self):
        import jax
        from jax.sharding import Mesh
        devs = jax.devices()
        arr = np.empty(self._ids.shape, dtype=object)
        for idx, pid in np.ndenumerate(self._ids):
            arr[idx] = devs[int(pid)]
        return Mesh(arr, tuple(self._dim_names))

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and np.array_equal(self._ids, other._ids)
                and self._dim_names == other._dim_names)

    def __repr__(self):
        return (f"ProcessMesh(shape={self.shape}, "
                f"dim_names={self._dim_names})")

    def __enter__(self):
        self._prev = _current["mesh"]
        _current["mesh"] = self
        return self

    def __exit__(self, *exc):
        _current["mesh"] = self._prev
        return False


def get_mesh() -> Optional[ProcessMesh]:
    return _current["mesh"]


def set_mesh(mesh: Optional[ProcessMesh]):
    _current["mesh"] = mesh


def _resolve_spec(process_mesh, shard_spec, ndim):
    """Accept both API generations: ``shard_spec`` axis-name list
    (["x", None, "y"]) or a v2.3 ``dims_mapping`` int list ([0, -1, 1])."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = process_mesh or get_mesh()
    if mesh is None:
        raise ValueError("no ProcessMesh: pass process_mesh= or enter a "
                         "`with ProcessMesh(...)` scope")
    names = mesh.dim_names
    spec = list(shard_spec if shard_spec is not None else [])
    spec += [None] * (ndim - len(spec))
    axes = []
    for s in spec[:ndim]:
        if s is None or s == -1:
            axes.append(None)
        elif isinstance(s, int):
            axes.append(names[s])       # dims_mapping form
        else:
            if s not in names:
                raise ValueError(f"unknown mesh dim {s!r}; have {names}")
            axes.append(s)
    return NamedSharding(mesh.jax_mesh(), P(*axes))


def shard_tensor(x, process_mesh: Optional[ProcessMesh] = None,
                 shard_spec=None, dist_attr=None, stop_gradient=None):
    """Annotate a tensor's placement (reference interface.py:34).

    Traced: becomes ``lax.with_sharding_constraint`` — GSPMD propagates
    from there. Eager: a sharded ``device_put``.
    ``dist_attr={"process_mesh": m, "dims_mapping": [...]}`` (v2.3 form)
    is accepted alongside ``shard_spec=["x", None]``.
    """
    import jax

    if dist_attr is not None:
        process_mesh = dist_attr.get("process_mesh", process_mesh)
        shard_spec = dist_attr.get("dims_mapping", shard_spec)
    is_tensor = isinstance(x, Tensor)
    arr = x._data if is_tensor else x
    sharding = _resolve_spec(process_mesh, shard_spec, arr.ndim)
    if isinstance(arr, jax.core.Tracer):
        out = jax.lax.with_sharding_constraint(arr, sharding)
    else:
        out = jax.device_put(arr, sharding)
    if is_tensor:
        t = Tensor(out, stop_gradient=x.stop_gradient)
        return t
    return out


def shard_op(op_fn, process_mesh: Optional[ProcessMesh] = None,
             in_specs=None, out_specs=None):
    """Annotate an op's inputs/outputs (reference interface.py:73):
    returns a wrapped callable that constrains tensor arguments and
    results; the op body itself stays GSPMD-propagated."""

    def wrapped(*args, **kwargs):
        def put(a, spec):
            if spec is not None and (isinstance(a, Tensor)
                                     or hasattr(a, "ndim")):
                return shard_tensor(a, process_mesh, spec)
            return a

        def pad(specs, n):
            # zip truncation would silently DROP args/outputs beyond the
            # spec list; absent specs mean "leave unconstrained"
            specs = list(specs)
            return specs + [None] * (n - len(specs))

        if in_specs is not None:
            args = tuple(put(a, s)
                         for a, s in zip(args, pad(in_specs, len(args))))
        out = op_fn(*args, **kwargs)
        if out_specs is None:
            return out
        if isinstance(out, (list, tuple)):
            specs = out_specs if isinstance(out_specs, (list, tuple)) \
                else [out_specs]
            return type(out)(put(o, s)
                             for o, s in zip(out, pad(specs, len(out))))
        return put(out, out_specs if not isinstance(out_specs, (list,
                   tuple)) else out_specs[0])

    return wrapped
