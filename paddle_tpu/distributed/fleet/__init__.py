"""``paddle.distributed.fleet`` — the distributed training facade.

Analog of the reference's ``fleet`` API
(python/paddle/distributed/fleet/base/fleet_base.py:144): ``init`` builds
the hybrid topology, ``distributed_model`` / ``distributed_optimizer`` wrap
user objects per the strategy.

TPU-native: init constructs the global Mesh (HybridCommunicateGroup);
distributed_model returns the model unchanged-but-annotated (parallelism is
sharding metadata, not wrapper layers issuing collectives);
distributed_optimizer returns a HybridParallelOptimizer whose ``step``
drives the ParallelEngine's single compiled SPMD step.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .. import env as _env
from ..spmd import ParallelEngine
from .base.strategy import DistributedStrategy
from .base.topology import HybridCommunicateGroup
from . import meta_parallel  # noqa: F401
from .utils import recompute as _recompute_mod  # noqa: F401
from .utils.recompute import recompute  # noqa: F401

__all__ = ["init", "DistributedStrategy", "HybridCommunicateGroup",
           "distributed_model", "distributed_optimizer", "get_hybrid_communicate_group",
           "worker_index", "worker_num", "is_first_worker", "barrier_worker",
           "meta_parallel", "recompute"]

_fleet_state = {"strategy": None, "hcg": None, "engine": None}


def init(role_maker=None, is_collective=False, strategy=None, log_level=20):
    """Reference: fleet_base.py:211. Builds the mesh from
    strategy.hybrid_configs (degrees of 1 collapse axes)."""
    strategy = strategy or DistributedStrategy()
    _env.init_parallel_env()
    h = strategy.hybrid_configs
    n_dev = _env.device_count()
    degrees = (h.dp_degree * h.pp_degree * h.sharding_degree *
               h.sep_degree * h.ep_degree * h.mp_degree)
    if degrees == 1 and n_dev > 1:
        h.dp_degree = n_dev  # pure data parallel default, reference-like
    hcg = HybridCommunicateGroup(
        dp_degree=h.dp_degree, pp_degree=h.pp_degree,
        sharding_degree=h.sharding_degree, sep_degree=h.sep_degree,
        ep_degree=h.ep_degree, mp_degree=h.mp_degree)
    _fleet_state["strategy"] = strategy
    _fleet_state["hcg"] = hcg
    return None


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _fleet_state["hcg"]


def _strategy() -> DistributedStrategy:
    if _fleet_state["strategy"] is None:
        init()
    return _fleet_state["strategy"]


def distributed_model(model):
    """Reference: fleet_base.py:947 wraps per topology (TensorParallel /
    PipelineParallel / ShardingParallel / DataParallel). Here the model's
    sharding metadata (mesh_axes set by meta_parallel layers; batch axis
    from the mesh) already encodes the strategy — we record the model for
    the engine and return it."""
    _fleet_state["model"] = model
    return model


def distributed_optimizer(optimizer, strategy=None):
    if strategy is not None:
        _fleet_state["strategy"] = strategy
    return HybridParallelOptimizer(optimizer)


class HybridParallelOptimizer:
    """Reference: hybrid_parallel_optimizer.py:172 (TP-aware global-norm
    clip + sharding-aware step). The engine's compiled step performs the
    clip inside the program; global norms across model/pipe shards are
    correct because the grads live on the mesh."""

    def __init__(self, inner):
        self._inner = inner
        self._engine: Optional[ParallelEngine] = None

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def _ensure_engine(self, loss_fn=None):
        if self._engine is None:
            strat = _strategy()
            model = _fleet_state.get("model")
            if model is None:
                raise RuntimeError(
                    "call fleet.distributed_model(model) before stepping "
                    "the distributed optimizer")
            zero = strat.sharding_configs.stage if strat.sharding else 0
            self._engine = ParallelEngine(
                model, self._inner, loss_fn,
                mesh=_fleet_state["hcg"].mesh, zero_stage=zero,
                recompute=strat.recompute)
            _fleet_state["engine"] = self._engine
        return self._engine

    def train_step(self, inputs, labels=(), loss_fn=None):
        """One hybrid-parallel step (the reference's model.train_batch)."""
        eng = self._ensure_engine(loss_fn)
        return eng.train_step(inputs, labels)

    def step(self):
        raise RuntimeError(
            "HybridParallelOptimizer runs whole steps: use "
            "train_step(inputs, labels) — forward/backward/update compile "
            "into one XLA program on TPU")

    def clear_grad(self):
        pass


def worker_index():
    return _env.get_rank()


def worker_num():
    return _env.get_world_size()


def is_first_worker():
    return _env.get_rank() == 0


def barrier_worker():
    from ..collective import barrier
    barrier()


# --------------------------------------------------------------------------
# reference fleet surface: the Fleet facade class, role makers, UtilBase,
# CTR data generators (reference fleet/__init__.py + base/role_maker.py,
# base/util_factory.py, data_generator/)
# --------------------------------------------------------------------------

from .base.topology import CommunicateTopology  # noqa: E402,F401


class Role:
    """Reference role_maker.Role constants."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class PaddleCloudRoleMaker:
    """Reference PaddleCloudRoleMaker: role from PADDLE_* env. On this
    backend every process is a collective WORKER (the PS server role is
    descoped; see README.md)."""

    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective

    def _worker_index(self):
        import os
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))

    def _worker_num(self):
        import os
        return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))

    def _role(self):
        return Role.WORKER

    def _is_worker(self):
        return True

    def _is_server(self):
        return False


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """Reference UserDefinedRoleMaker: explicit rank/world size."""

    def __init__(self, is_collective=True, current_id=0, worker_num=1,
                 role=Role.WORKER, **kwargs):
        super().__init__(is_collective=is_collective)
        self._cur = int(current_id)
        self._num = int(worker_num)

    def _worker_index(self):
        return self._cur

    def _worker_num(self):
        return self._num


class UtilBase:
    """Reference base/util_factory.py UtilBase: cross-worker helpers
    over the collective API."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np
        from .. import env as _env
        if not _env.is_initialized() or _env.get_world_size() <= 1:
            return np.asarray(input)
        from ..collective import ReduceOp, all_reduce as _ar
        from ...framework.tensor import Tensor
        op = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX,
              "min": ReduceOp.MIN}[mode.lower()]
        t = Tensor(np.asarray(input))
        _ar(t, op=op)
        return np.asarray(t.numpy())

    def barrier(self, comm_world="worker"):
        from .. import env as _env
        if _env.is_initialized():
            from ..collective import barrier as _b
            _b()

    def all_gather(self, input, comm_world="worker"):
        import numpy as np
        from .. import env as _env
        if not _env.is_initialized() or _env.get_world_size() <= 1:
            return [input]
        from ..collective import all_gather as _ag
        from ...framework.tensor import Tensor
        out = []
        _ag(out, Tensor(np.asarray(input)))
        return [np.asarray(o.numpy()) for o in out]

    def get_file_shard(self, files):
        """Split a file list evenly across workers (reference
        UtilBase.get_file_shard)."""
        n = worker_num()
        i = worker_index()
        per, rem = divmod(len(files), n)
        start = i * per + min(i, rem)
        return list(files[start:start + per + (1 if i < rem else 0)])


util = UtilBase()


class Fleet:
    """Reference fleet_base.Fleet — the class behind the module-level
    singleton. Methods delegate to this module's functions so both
    ``fleet.init(...)`` and ``Fleet().init(...)`` work."""

    def init(self, role_maker=None, is_collective=False, strategy=None,
             log_level=20):
        return init(role_maker, is_collective, strategy, log_level)

    def distributed_model(self, model):
        return distributed_model(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        return distributed_optimizer(optimizer, strategy)

    def worker_index(self):
        return worker_index()

    def worker_num(self):
        return worker_num()

    def is_first_worker(self):
        return is_first_worker()

    def barrier_worker(self):
        return barrier_worker()

    @property
    def util(self):
        return util


class MultiSlotDataGenerator:
    """CTR slot-format data generator (reference fleet/data_generator/
    data_generator.py): subclass, implement ``generate_sample(line)``
    yielding [(slot_name, [feasigns...]), ...]; ``run_from_stdin`` /
    ``run_from_memory`` emit the MultiSlot text protocol."""

    def generate_sample(self, line):
        raise NotImplementedError

    def _format(self, sample) -> str:
        parts = []
        for _name, feasigns in sample:
            parts.append(str(len(feasigns)))
            parts.extend(str(v) for v in feasigns)
        return " ".join(parts)

    def _emit(self, lines, out):
        for line in lines:
            gen = self.generate_sample(line)
            for sample in (gen() if callable(gen) else gen):
                out.write(self._format(sample) + "\n")

    def run_from_stdin(self):
        import sys
        self._emit(sys.stdin, sys.stdout)

    def run_from_memory(self, lines):
        import io
        out = io.StringIO()
        self._emit(lines, out)
        return out.getvalue()


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    """String-feasign variant (reference data_generator.py)."""


__all__ += ["Fleet", "Role", "PaddleCloudRoleMaker",
            "UserDefinedRoleMaker", "UtilBase", "CommunicateTopology",
            "MultiSlotDataGenerator", "MultiSlotStringDataGenerator",
            "util"]
