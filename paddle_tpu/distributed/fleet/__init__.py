"""``paddle.distributed.fleet`` — the distributed training facade.

Analog of the reference's ``fleet`` API
(python/paddle/distributed/fleet/base/fleet_base.py:144): ``init`` builds
the hybrid topology, ``distributed_model`` / ``distributed_optimizer`` wrap
user objects per the strategy.

TPU-native: init constructs the global Mesh (HybridCommunicateGroup);
distributed_model returns the model unchanged-but-annotated (parallelism is
sharding metadata, not wrapper layers issuing collectives);
distributed_optimizer returns a HybridParallelOptimizer whose ``step``
drives the ParallelEngine's single compiled SPMD step.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .. import env as _env
from ..spmd import ParallelEngine
from .base.strategy import DistributedStrategy
from .base.topology import HybridCommunicateGroup
from . import meta_parallel  # noqa: F401
from .utils import recompute as _recompute_mod  # noqa: F401
from .utils.recompute import recompute  # noqa: F401

__all__ = ["init", "DistributedStrategy", "HybridCommunicateGroup",
           "distributed_model", "distributed_optimizer", "get_hybrid_communicate_group",
           "worker_index", "worker_num", "is_first_worker", "barrier_worker",
           "meta_parallel", "recompute"]

_fleet_state = {"strategy": None, "hcg": None, "engine": None}


def init(role_maker=None, is_collective=False, strategy=None, log_level=20):
    """Reference: fleet_base.py:211. Builds the mesh from
    strategy.hybrid_configs (degrees of 1 collapse axes)."""
    strategy = strategy or DistributedStrategy()
    _env.init_parallel_env()
    h = strategy.hybrid_configs
    n_dev = _env.device_count()
    degrees = (h.dp_degree * h.pp_degree * h.sharding_degree *
               h.sep_degree * h.ep_degree * h.mp_degree)
    if degrees == 1 and n_dev > 1:
        h.dp_degree = n_dev  # pure data parallel default, reference-like
    hcg = HybridCommunicateGroup(
        dp_degree=h.dp_degree, pp_degree=h.pp_degree,
        sharding_degree=h.sharding_degree, sep_degree=h.sep_degree,
        ep_degree=h.ep_degree, mp_degree=h.mp_degree)
    _fleet_state["strategy"] = strategy
    _fleet_state["hcg"] = hcg
    return None


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _fleet_state["hcg"]


def _strategy() -> DistributedStrategy:
    if _fleet_state["strategy"] is None:
        init()
    return _fleet_state["strategy"]


def distributed_model(model):
    """Reference: fleet_base.py:947 wraps per topology (TensorParallel /
    PipelineParallel / ShardingParallel / DataParallel). Here the model's
    sharding metadata (mesh_axes set by meta_parallel layers; batch axis
    from the mesh) already encodes the strategy — we record the model for
    the engine and return it."""
    _fleet_state["model"] = model
    return model


def distributed_optimizer(optimizer, strategy=None):
    if strategy is not None:
        _fleet_state["strategy"] = strategy
    return HybridParallelOptimizer(optimizer)


class HybridParallelOptimizer:
    """Reference: hybrid_parallel_optimizer.py:172 (TP-aware global-norm
    clip + sharding-aware step). The engine's compiled step performs the
    clip inside the program; global norms across model/pipe shards are
    correct because the grads live on the mesh."""

    def __init__(self, inner):
        self._inner = inner
        self._engine: Optional[ParallelEngine] = None

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def _ensure_engine(self, loss_fn=None):
        if self._engine is None:
            strat = _strategy()
            model = _fleet_state.get("model")
            if model is None:
                raise RuntimeError(
                    "call fleet.distributed_model(model) before stepping "
                    "the distributed optimizer")
            zero = strat.sharding_configs.stage if strat.sharding else 0
            self._engine = ParallelEngine(
                model, self._inner, loss_fn,
                mesh=_fleet_state["hcg"].mesh, zero_stage=zero,
                recompute=strat.recompute)
            _fleet_state["engine"] = self._engine
        return self._engine

    def train_step(self, inputs, labels=(), loss_fn=None):
        """One hybrid-parallel step (the reference's model.train_batch)."""
        eng = self._ensure_engine(loss_fn)
        return eng.train_step(inputs, labels)

    def step(self):
        raise RuntimeError(
            "HybridParallelOptimizer runs whole steps: use "
            "train_step(inputs, labels) — forward/backward/update compile "
            "into one XLA program on TPU")

    def clear_grad(self):
        pass


def worker_index():
    return _env.get_rank()


def worker_num():
    return _env.get_world_size()


def is_first_worker():
    return _env.get_rank() == 0


def barrier_worker():
    from ..collective import barrier
    barrier()
