"""Activation recompute.

Analog of the reference's ``fleet/utils/recompute.py:207,350`` — a PyLayer
that stashes RNG state and replays forward during backward. TPU-native:
``jax.checkpoint`` (remat) expresses exactly this to XLA, RNG determinism
included because random ops consume explicitly-folded keys
(framework/random.py), so the replay sees identical streams.
"""
from __future__ import annotations

from typing import Sequence

import jax

from ....framework.tensor import Tensor, no_grad_guard
from ....nn.layer.layers import Layer

__all__ = ["recompute", "RecomputeLayer"]


def recompute(function, *args, **kwargs):
    """Run ``function(*args)`` under remat: activations inside are
    rematerialised during backward instead of stored.

    Works inside jitted train steps (the normal TPU path). The wrapped
    function must be Tensor-in/Tensor-out.
    """
    kwargs.pop("preserve_rng_state", True)  # parity; replay is always exact
    tensor_args = [a for a in args if isinstance(a, Tensor)]
    # Eager (untraced) call: the tape engine owns residual lifetime, remat
    # has nothing to trade — run the function directly.
    if not any(isinstance(t._data, jax.core.Tracer) for t in tensor_args):
        return function(*args, **kwargs)

    @jax.checkpoint
    def inner(*arrays):
        ins = list(args)
        it = iter(arrays)
        ins = [Tensor(next(it), stop_gradient=a.stop_gradient)
               if isinstance(a, Tensor) else a for a in ins]
        out = function(*ins, **kwargs)
        if isinstance(out, (list, tuple)):
            return tuple(o._data for o in out)
        return out._data

    out = inner(*[t._data for t in tensor_args])
    if isinstance(out, tuple):
        return tuple(Tensor(o, stop_gradient=False) for o in out)
    return Tensor(out, stop_gradient=False)


class RecomputeLayer(Layer):
    """Wrap a sublayer so its forward runs under remat."""

    def __init__(self, layer: Layer):
        super().__init__()
        self.inner = layer

    def forward(self, *args, **kwargs):
        return recompute(self.inner, *args, **kwargs)
