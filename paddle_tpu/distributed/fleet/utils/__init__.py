from .fs import DistributedInfer, HDFSClient, LocalFS  # noqa: F401
from .recompute import RecomputeLayer, recompute  # noqa: F401
