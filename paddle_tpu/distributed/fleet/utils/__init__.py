from .recompute import RecomputeLayer, recompute  # noqa: F401
