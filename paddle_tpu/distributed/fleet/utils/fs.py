"""Filesystem clients (reference: python/paddle/distributed/fleet/utils/
fs.py — LocalFS over os/shutil, HDFSClient shelling out to `hadoop fs`).

LocalFS is fully real. HDFSClient drives a ``hadoop`` binary when one
exists on PATH (same mechanism as the reference); without one, every
call raises with that diagnosis instead of hanging on a missing
subprocess.
"""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Optional, Tuple

__all__ = ["LocalFS", "HDFSClient", "DistributedInfer"]


class ExecuteError(Exception):
    pass


class LocalFS:
    """Reference fs.py LocalFS — local filesystem with the FS client
    interface checkpoint/elastic code uses."""

    def ls_dir(self, fs_path) -> Tuple[List[str], List[str]]:
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, name))
             else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path) -> None:
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, src, dst) -> None:
        os.rename(src, dst)

    def delete(self, fs_path) -> None:
        if self.is_dir(fs_path):
            shutil.rmtree(fs_path)
        elif self.is_file(fs_path):
            os.remove(fs_path)

    def need_upload_download(self) -> bool:
        return False

    def is_file(self, fs_path) -> bool:
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path) -> bool:
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path) -> bool:
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True) -> None:
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FileExistsError(fs_path)
            return
        with open(fs_path, "w"):
            pass

    def upload(self, local_path, fs_path) -> None:
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path) -> None:
        shutil.copy(fs_path, local_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=True):
        if test_exists and not self.is_exist(src_path):
            raise FileNotFoundError(src_path)
        if self.is_exist(dst_path):
            if not overwrite:
                # reference raises FSFileExistsError here — a checkpoint
                # rotation must never silently clobber the destination
                raise FileExistsError(
                    f"{dst_path} exists (pass overwrite=True)")
            self.delete(dst_path)
        shutil.move(src_path, dst_path)

    def cat(self, fs_path) -> str:
        with open(fs_path) as f:
            return f.read()

    def list_dirs(self, fs_path) -> List[str]:
        return self.ls_dir(fs_path)[0]


class HDFSClient:
    """Reference fs.py HDFSClient: every operation shells out to
    ``hadoop fs`` with the configured name node. Works when a hadoop
    binary exists; raises a clear diagnosis otherwise."""

    def __init__(self, hadoop_home: Optional[str] = None, configs=None,
                 time_out=5 * 60 * 1000, sleep_inter=1000):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else shutil.which("hadoop")
        self._configs = configs or {}
        self._timeout = time_out / 1000.0

    def _run(self, *args) -> str:
        if not self._hadoop or not os.path.exists(self._hadoop):
            raise ExecuteError(
                "no hadoop binary available (pass hadoop_home= or put "
                "`hadoop` on PATH); this environment has no HDFS — use "
                "LocalFS or sharded checkpoints (distributed/checkpoint)")
        cmd = [self._hadoop, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", f"{k}={v}"]
        cmd += list(args)
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=self._timeout)
        if proc.returncode != 0:
            raise ExecuteError(f"hadoop {' '.join(args)} failed: "
                               f"{proc.stderr[-500:]}")
        return proc.stdout

    def ls_dir(self, fs_path):
        out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run("-rm", "-r", "-f", fs_path)

    def is_exist(self, fs_path) -> bool:
        try:
            self._run("-test", "-e", fs_path)
            return True
        except ExecuteError:
            return False

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def need_upload_download(self) -> bool:
        return True


class DistributedInfer:
    """Reference utils/ps_util.py DistributedInfer: swaps a trained PS
    program for inference. On this backend inference programs are
    for-test clones already; the facade wires that path."""

    def __init__(self, main_program=None, startup_program=None):
        from ... import static
        self._main = main_program or static.default_main_program()

    def init_distributed_infer_env(self, exe, loss, role_maker=None,
                                   dirname=None):
        if dirname is not None:
            from ... import static
            static.load(self._main, dirname)

    def get_dist_infer_program(self):
        return self._main.clone(for_test=True)
