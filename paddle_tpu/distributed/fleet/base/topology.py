"""Hybrid-parallel topology.

Analog of the reference's ``HybridCommunicateGroup``
(python/paddle/distributed/fleet/base/topology.py:55,134) which carves the
world into dp/pp/sharding/mp comm groups and a p2p ring.

TPU-native: the topology IS a ``jax.sharding.Mesh`` whose axis order
places the highest-traffic axis ("model") innermost on ICI, then
sequence, sharding, pipe, and data outermost (DCN-friendly) — the same
ordering rationale as the reference's ["data","pipe","sharding","model"].
Every "communication group" is just an axis name; rank coordinates are
device coordinates in the mesh.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ... import env as _env

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]

_AXIS_ORDER = ["data", "pipe", "sharding", "sep", "expert", "model"]


class CommunicateTopology:
    def __init__(self, hybrid_group_names=None, dims=None):
        self._names = list(hybrid_group_names or _AXIS_ORDER)
        self._dims = list(dims or [1] * len(self._names))

    def get_hybrid_group_names(self):
        return self._names

    def get_dim(self, axis_name):
        return self._dims[self._names.index(axis_name)]

    def world_size(self):
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs):
        coords = [kwargs.get(n, 0) for n in self._names]
        return int(np.ravel_multi_index(coords, self._dims))

    def get_coord(self, rank):
        return tuple(int(c) for c in
                     np.unravel_index(rank, self._dims))


class HybridCommunicateGroup:
    """Builds the global mesh for a dp/pp/sharding/sep/ep/mp topology."""

    def __init__(self, topology: Optional[CommunicateTopology] = None,
                 dp_degree=1, pp_degree=1, sharding_degree=1, sep_degree=1,
                 ep_degree=1, mp_degree=1, devices=None):
        if topology is not None:
            dims = {n: topology.get_dim(n)
                    for n in topology.get_hybrid_group_names()}
            dp_degree = dims.get("data", 1)
            pp_degree = dims.get("pipe", 1)
            sharding_degree = dims.get("sharding", 1)
            sep_degree = dims.get("sep", 1)
            ep_degree = dims.get("expert", 1)
            mp_degree = dims.get("model", 1)
        self._degrees = {
            "data": dp_degree, "pipe": pp_degree,
            "sharding": sharding_degree, "sep": sep_degree,
            "expert": ep_degree, "model": mp_degree,
        }
        self._topo = CommunicateTopology(
            _AXIS_ORDER, [self._degrees[n] for n in _AXIS_ORDER])
        self.nranks = self._topo.world_size()
        self.mesh = _env.build_mesh(
            {n: self._degrees[n] for n in _AXIS_ORDER}, devices=devices)
        _env.set_topology(self)
        self.global_rank = _env.get_rank()

    # degree/rank accessors mirroring the reference API ---------------------
    def get_data_parallel_world_size(self):
        return self._degrees["data"]

    def get_model_parallel_world_size(self):
        return self._degrees["model"]

    def get_pipe_parallel_world_size(self):
        return self._degrees["pipe"]

    def get_sharding_parallel_world_size(self):
        return self._degrees["sharding"]

    def get_sep_parallel_world_size(self):
        return self._degrees["sep"]

    def get_expert_parallel_world_size(self):
        return self._degrees["expert"]

    def _coord(self):
        return self._topo.get_coord(self.global_rank % self.nranks)

    def get_data_parallel_rank(self):
        return self._coord()[_AXIS_ORDER.index("data")]

    def get_model_parallel_rank(self):
        return self._coord()[_AXIS_ORDER.index("model")]

    def get_stage_id(self):
        return self._coord()[_AXIS_ORDER.index("pipe")]

    def get_sharding_parallel_rank(self):
        return self._coord()[_AXIS_ORDER.index("sharding")]

    # group objects (axis handles) ------------------------------------------
    def get_data_parallel_group(self):
        from ..collective import new_group
        return new_group(axis_name="data")

    def get_model_parallel_group(self):
        from ..collective import new_group
        return new_group(axis_name="model")

    def get_pipe_parallel_group(self):
        from ..collective import new_group
        return new_group(axis_name="pipe")

    def get_sharding_parallel_group(self):
        from ..collective import new_group
        return new_group(axis_name="sharding")

    def get_check_parallel_group(self):
        from ..collective import new_group
        return new_group(axis_name=None)

    def topology(self):
        return self._topo

    def __repr__(self):
        d = {k: v for k, v in self._degrees.items() if v > 1}
        return f"HybridCommunicateGroup({d or 'single-device'})"
