"""DistributedStrategy.

Analog of the reference's protobuf-backed ``DistributedStrategy``
(framework/distributed_strategy.proto:278, python wrapper
fleet/base/distributed_strategy.py:110 — ~40 toggle+config pairs). The
protobuf indirection collapses into a plain dataclass; the toggles that
exist only to drive CUDA-era executor rewrites (fuse_allreduce, DGC,
localsgd…) are accepted for compatibility and recorded, but XLA makes the
corresponding decisions during compilation.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

__all__ = ["DistributedStrategy"]


@dataclass
class HybridConfig:
    dp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sep_degree: int = 1       # sequence/context parallel (NEW vs reference)
    ep_degree: int = 1        # expert parallel


@dataclass
class RecomputeConfig:
    checkpoints: list = field(default_factory=list)


@dataclass
class AmpConfig:
    init_loss_scaling: float = 2.0 ** 15
    use_dynamic_loss_scaling: bool = True
    custom_white_list: list = field(default_factory=list)
    custom_black_list: list = field(default_factory=list)
    use_pure_fp16: bool = False
    dtype: str = "bfloat16"
    level: str = "O1"


@dataclass
class PipelineConfig:
    accumulate_steps: int = 1
    micro_batch_size: int = 1
    schedule_mode: str = "1F1B"


@dataclass
class ShardingConfig:
    stage: int = 1
    degree: int = 1
    offload: bool = False


class DistributedStrategy:
    def __init__(self):
        self.amp = False
        self.amp_configs = AmpConfig()
        self.recompute = False
        self.recompute_configs = RecomputeConfig()
        self.pipeline = False
        self.pipeline_configs = PipelineConfig()
        self.sharding = False
        self.sharding_configs = ShardingConfig()
        self.tensor_parallel = False
        self.hybrid_configs = HybridConfig()
        self.gradient_merge = False
        self.gradient_merge_configs: Dict[str, Any] = {"k_steps": 1}
        self.lamb = False
        self.dgc = False                 # accepted; no-op under XLA
        self.localsgd = False            # accepted; no-op under XLA
        self.fuse_all_reduce_ops = True  # XLA fuses collectives itself
        self.find_unused_parameters = False
        self.heter_ccl_mode = False

    def __setattr__(self, key, value):
        if key == "hybrid_configs" and isinstance(value, dict):
            cfg = self.__dict__.get("hybrid_configs") or HybridConfig()
            for k, v in value.items():
                setattr(cfg, k, v)
            object.__setattr__(self, key, cfg)
            return
        if key == "pipeline_configs" and isinstance(value, dict):
            cfg = self.__dict__.get("pipeline_configs") or PipelineConfig()
            for k, v in value.items():
                setattr(cfg, k, v)
            object.__setattr__(self, key, cfg)
            return
        if key == "sharding_configs" and isinstance(value, dict):
            cfg = self.__dict__.get("sharding_configs") or ShardingConfig()
            for k, v in value.items():
                setattr(cfg, k, v)
            object.__setattr__(self, key, cfg)
            return
        if key == "amp_configs" and isinstance(value, dict):
            cfg = self.__dict__.get("amp_configs") or AmpConfig()
            for k, v in value.items():
                setattr(cfg, k, v)
            object.__setattr__(self, key, cfg)
            return
        object.__setattr__(self, key, value)

    def __repr__(self):
        h = self.hybrid_configs
        return (f"DistributedStrategy(dp={h.dp_degree}, mp={h.mp_degree}, "
                f"pp={h.pp_degree}, sharding={h.sharding_degree}, "
                f"sep={h.sep_degree}, ep={h.ep_degree}, amp={self.amp}, "
                f"recompute={self.recompute})")
