"""Model-parallel RNG state tracking.

Analog of the reference's ``get_rng_state_tracker``
(fleet/meta_parallel/parallel_layers/random.py): named, seedable streams so
e.g. dropout differs across mp ranks inside sharded regions but matches
across dp replicas. On TPU the functional PRNG makes a stream = a folded
key; per-rank decorrelation folds in the mesh axis index inside the traced
program.
"""
from __future__ import annotations

import contextlib

import jax

from .....framework import random as _random

__all__ = ["RNGStatesTracker", "get_rng_state_tracker",
           "model_parallel_random_seed"]


class RNGStatesTracker:
    def __init__(self):
        self._seeds = {}

    def add(self, name, seed):
        self._seeds[name] = int(seed)

    def get_states_tracker(self):
        return dict(self._seeds)

    @contextlib.contextmanager
    def rng_state(self, name="model-parallel-rng"):
        """Route random ops to the named stream; inside a sharded program
        the stream additionally folds in the "model" axis index so each mp
        rank draws distinct values (the reference keeps per-rank CUDA seeds
        for the same purpose)."""
        seed = self._seeds.get(name, 0)
        key = jax.random.key(seed)
        try:
            idx = jax.lax.axis_index("model")
            key = jax.random.fold_in(key, idx)
        except NameError:
            pass  # not inside a "model"-axis context
        with _random.rng_guard(key):
            yield


_tracker = RNGStatesTracker()
_tracker.add("global_seed", 0)
_tracker.add("model-parallel-rng", 1)


def get_rng_state_tracker():
    return _tracker


def model_parallel_random_seed(seed=None):
    base = seed if seed is not None else 0
    _tracker._seeds.clear()
    _tracker.add("global_seed", base)
    _tracker.add("model-parallel-rng", base + 1)
    _random.seed(base)
