"""Declarative pipeline stage partitioning.

Analog of the reference's ``PipelineLayer`` / ``LayerDesc`` /
``SharedLayerDesc`` (fleet/meta_parallel/parallel_layers/pp_layers.py:58-233)
— declare the model as an ordered layer list, segment it into stages.

TPU-native: a PipelineLayer still runs as ONE sequential program on a
single device (debug/parity path). Sharded pipeline execution stacks the
uniform trunk's per-stage parameters along a leading "pipe"-sharded axis
and runs the collective-permute schedule in
``meta_parallel/pipeline_parallel.py``.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

from ..... import nn
from .....framework.tensor import Tensor

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer"]


class LayerDesc:
    """Lazy layer constructor (reference pp_layers.py:LayerDesc)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs
        if not issubclass(layer_cls, nn.Layer) and not callable(layer_cls):
            raise TypeError("LayerDesc needs a Layer subclass")

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Layer shared between stages (reference: tied embeddings). Under
    SPMD the sharing is literal — one parameter object, replicated over
    "pipe" — so no grad-sync ops are needed."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr
                 ="weight", *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(nn.Layer):
    """Reference pp_layers.py:PipelineLayer — ``SegmentLayers`` uniform/
    custom cut, ``get_stage_layers``. Single-device forward is the exact
    sequential model, so pipeline loss parity is testable everywhere.
    """

    def __init__(self, layers: Sequence, num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method="uniform",
                 recompute_interval=0, num_virtual_pipeline_stages=None):
        super().__init__()
        self._descs = list(layers)
        self._loss_fn = loss_fn
        self._num_stages = num_stages or (
            topology.get_dim("pipe") if topology else 1)
        self._shared = {}
        built = []
        for d in self._descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    layer = self._shared[d.layer_name]
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                built.append((layer, d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None))
            elif isinstance(d, nn.Layer):
                built.append((d, None))
            elif callable(d):
                built.append((d, None))
            else:
                raise TypeError(f"bad pipeline entry: {d!r}")
        self.run_function = built
        for i, (layer, _) in enumerate(built):
            if isinstance(layer, nn.Layer):
                self.add_sublayer(str(i), layer)
        self._segment(seg_method)

    def _segment(self, method):
        n = len(self.run_function)
        p = self._num_stages
        if isinstance(method, str) and method.startswith("layer:"):
            # cut at layers whose class name matches (reference custom cut)
            name = method.split(":", 1)[1]
            idxs = [i for i, (l, _) in enumerate(self.run_function)
                    if type(l).__name__ == name]
            if len(idxs) < p:
                raise ValueError(
                    f"need >= {p} '{name}' layers to cut {p} stages")
            per = len(idxs) // p
            bounds = [0] + [idxs[i * per] for i in range(1, p)] + [n]
        else:  # uniform
            per = (n + p - 1) // p
            bounds = [min(i * per, n) for i in range(p)] + [n]
        self.segment_parts = bounds

    def get_stage_bounds(self, stage):
        return self.segment_parts[stage], self.segment_parts[stage + 1]

    def get_stage_layers(self, stage):
        lo, hi = self.get_stage_bounds(stage)
        return [l for l, _ in self.run_function[lo:hi]]

    @property
    def num_stages(self):
        return self._num_stages

    def forward(self, x, *args):
        for layer, ffn in self.run_function:
            if ffn is not None:
                x = ffn(layer, x)
            elif isinstance(layer, nn.Layer):
                x = layer(x)
            else:
                x = layer(x)
        return x
