"""Megatron-style tensor-parallel layers.

Analog of the reference's ``VocabParallelEmbedding`` /
``ColumnParallelLinear`` / ``RowParallelLinear`` / ``ParallelCrossEntropy``
(fleet/meta_parallel/parallel_layers/mp_layers.py:30,95,171,251), which wrap
explicit collectives (_c_identity/_mp_allreduce/_c_softmax_with_cross_entropy,
distributed/collective.py:1038-1357).

TPU-native mechanism: layers DECLARE shardings instead of issuing
collectives. Each parameter carries ``mesh_axes`` (a PartitionSpec tuple
over the hybrid mesh axes); activations get ``with_sharding_constraint``
hints at the points where the reference inserted c_ops. GSPMD then emits
the identical psum/all-gather schedule on ICI — the 1.2k LoC of manual
collective plumbing in the reference reduces to annotations, and the
sharded-softmax CE trick falls out of the partitioner.

Layers behave identically on a single device (annotations are no-ops), so
the same model runs eagerly for debugging.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..... import nn
from .....framework.dispatch import call_op
from .....framework.tensor import Parameter, Tensor
from .....nn import functional as F
from .... import env as _env

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy",
           "mark_sharding", "constrain"]


def mark_sharding(param: Parameter, *axes):
    """Attach a PartitionSpec (tuple of mesh-axis names / None per dim)."""
    param.mesh_axes = tuple(axes)
    return param


def constrain(x, *axes):
    """with_sharding_constraint on an activation, no-op without a mesh.

    This is the TPU analog of the reference's _c_identity/_c_split markers:
    it pins where the partitioner must place the tensor, which determines
    which collectives GSPMD inserts around it.
    """
    mesh = _env.get_mesh()
    if mesh is None or int(np.prod(mesh.devices.shape)) <= 1:
        return x
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def _constrain(d):
        return jax.lax.with_sharding_constraint(
            d, NamedSharding(mesh, P(*axes)))

    if not isinstance(x, Tensor):
        try:
            return _constrain(x)
        except ValueError:
            return x  # outside jit, incompatible placement: best-effort
    try:
        # differentiable_apply threads the EAGER tape: a bare
        # Tensor(out) here would sever grads for every constrain user
        # (e.g. ShardedEmbedding trained in a plain eager loop on a
        # multi-device mesh)
        from .....autograd import differentiable_apply
        return differentiable_apply(_constrain, x)
    except ValueError:
        return x


class VocabParallelEmbedding(nn.Layer):
    """Embedding with the vocab dimension sharded over the "model" axis.

    Reference: mp_layers.py:30 — shards rows, masks out-of-range ids,
    allreduces partial lookups. Here the table is annotated
    ("model", None) and GSPMD partitions the gather + emits the psum.
    """

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num = num_embeddings
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        mark_sharding(self.weight, "model", None)

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return constrain(out, "data", None, None)


class ColumnParallelLinear(nn.Layer):
    """Linear with output features sharded over "model" (reference
    mp_layers.py:95). gather_output=True appends the all-gather the
    reference's _c_concat performs."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, mp_group=None,
                 name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        mark_sharding(self.weight, None, "model")
        if has_bias:
            self.bias = self.create_parameter(
                [out_features], is_bias=True)
            mark_sharding(self.bias, "model")
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = constrain(out, *(None,) * (len(out.shape)))
        else:
            out = constrain(out, *(None,) * (len(out.shape) - 1), "model")
        return out


class RowParallelLinear(nn.Layer):
    """Linear with input features sharded over "model" (reference
    mp_layers.py:171): partial products are psum'd — GSPMD emits that
    reduction because the contracted dim is sharded."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, mp_group=None,
                 name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        mark_sharding(self.weight, "model", None)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            mark_sharding(self.bias)
        else:
            self.bias = None

    def forward(self, x):
        if not self.input_is_parallel:
            x = constrain(x, *(None,) * (len(x.shape) - 1), "model")
        out = F.linear(x, self.weight, self.bias)
        return constrain(out, *(None,) * (len(out.shape)))


class ParallelCrossEntropy(nn.Layer):
    """Vocab-parallel softmax cross-entropy (reference mp_layers.py:251 →
    c_softmax_with_cross_entropy CUDA kernel doing max/sum psums and
    masked local gather).

    Annotating logits as vocab-sharded is sufficient: the partitioner
    decomposes log_softmax + gather into exactly that max-psum/sum-psum/
    masked-gather schedule.
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        input = constrain(
            input, *(None,) * (len(input.shape) - 1), "model")
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
