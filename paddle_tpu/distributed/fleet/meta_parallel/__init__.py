"""meta_parallel — hybrid-parallel building blocks.

Analog of the reference's ``fleet/meta_parallel/``.
"""
from .parallel_layers.mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding, constrain, mark_sharding,
)
from .parallel_layers.pp_layers import (  # noqa: F401
    LayerDesc, PipelineLayer, SharedLayerDesc,
)
from .parallel_layers.random import (  # noqa: F401
    RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed,
)
