"""Pipeline-parallel execution over the "pipe" mesh axis.

Analog of the reference's ``PipelineParallel`` 1F1B scheduler
(fleet/meta_parallel/pipeline_parallel.py:31, forward_backward_pipeline:82)
and its P2P layer (pp_utils/p2p_communication.py): warmup/steady/cooldown
micro-batch phases exchanging activations with batched ncclSend/Recv.

TPU-native schedule: the whole pipeline is ONE differentiable SPMD
program. Inside ``shard_map`` over the "pipe" axis, every rank applies its
own stage parameters each tick (a ``lax.scan`` over M+P-1 ticks, so
compile time does not grow with the micro-batch count); activations hop
stages via ``lax.ppermute`` (collective-permute rides ICI neighbours).
Reverse-mode AD transposes the scan into the mirrored backward pipeline —
ppermute's transpose is the reverse permute — so forward+backward behave
like GPipe with M micro-batches (bubble (P-1)/(M+P-1) on each side).

Memory parity with 1F1B (r2 verdict item 5): 1F1B exists to bound live
activation memory to O(P) micro-batches instead of GPipe's O(M). Here the
same bound comes from ``recompute=True`` (the default): jax.checkpoint on
each stage application makes the scan's saved residuals one activation
per tick — O(activation) per live micro-batch slot, i.e. the 1F1B bound —
while XLA overlaps the permutes with compute. ``recompute`` is a constructor
knob (PipelineParallel(..., recompute=False)) for small models where
storing everything is faster.

Stage structure: stages may hold DIFFERENT layer counts (non-uniform
segmentation, e.g. ``seg_method="layer:Block"`` cuts or uneven uniform
splits) — shorter stages pad to the longest with gated identity slots.
Layers at the same within-stage index must share one parameter structure
(transformer trunks do). Tied embed/head (reference SharedLayerDesc):
pass ``embed``/``head`` layers that literally share Parameter objects —
the engine aliases shared leaves so the tied weight is ONE tree leaf and
jax sums its two gradient paths, exactly the reference's shared-weight
allreduce. Under SPMD, "stage residency" of embed/head is a sharding
choice, not a placement: the tied parameters are kept replicated over
"pipe" (no p2p of weights, GSPMD free to shard them over other axes).
"""
from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ....framework.tensor import Tensor, no_grad_guard
from ....nn.layer.layers import Layer, functional_call, get_params_tree
from ... import env as _env
from .parallel_layers.pp_layers import PipelineLayer

__all__ = ["PipelineParallel", "pipeline_forward"]


def _stack_stage_params(pipeline: PipelineLayer):
    """Stack per-stage parameter trees along a leading pipe axis.

    Stages may hold different layer counts: every stage is padded to the
    longest stage's count ``k_max`` with zero parameters, and a gate
    matrix [P, k_max] marks which slots are real. Returns
    (templates, stacked, gates) where templates are the longest stage's
    layer objects (reused for functional application on every rank) and
    stacked[j][pname] has shape [P, ...].
    """
    import jax.numpy as jnp

    P = pipeline.num_stages
    stage_layers = [pipeline.get_stage_layers(s) for s in range(P)]
    counts = [len(sl) for sl in stage_layers]
    k_max = max(counts)
    ref_stage = counts.index(k_max)
    templates = stage_layers[ref_stage]
    gates = np.zeros((P, k_max), np.bool_)
    stacked = []
    for j in range(k_max):
        names0 = [n for n, _ in templates[j].named_parameters()]
        per_stage = []
        for s in range(P):
            if j < counts[s]:
                ps = dict(stage_layers[s][j].named_parameters())
                if sorted(ps.keys()) != sorted(names0):
                    raise NotImplementedError(
                        f"stage {s} layer {j} parameter structure differs "
                        f"from stage {ref_stage} — layers at the same "
                        "within-stage index must be structurally uniform")
                per_stage.append({n: ps[n]._data for n in names0})
                gates[s, j] = True
            else:
                # padded identity slot: zero params, gated off
                tp = dict(templates[j].named_parameters())
                per_stage.append(
                    {n: jnp.zeros_like(tp[n]._data) for n in names0})
        stacked.append({
            n: jnp.stack([per_stage[s][n] for s in range(P)])
            for n in names0})
    return templates, stacked, gates


def pipeline_forward(templates: List[Layer], stacked_params,
                     x_microbatches, mesh, n_stages: int, recompute=True,
                     gates=None, axis_name="pipe"):
    """Differentiable GPipe schedule: x_microbatches [M, mb, ...] ->
    outputs [M, mb, ...]. Runs inside jit; all other mesh axes stay under
    GSPMD (shard_map auto mode). ``gates``: optional [P, k] bool — False
    slots apply identity (non-uniform stage support)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as PS

    M = x_microbatches.shape[0]
    P = n_stages
    k = len(templates)
    if gates is None:
        gates = np.ones((P, k), np.bool_)
    gates = jnp.asarray(gates)

    def stage_apply(local_params, local_gates, state):
        def apply(st):
            h = st
            for j, tmpl in enumerate(templates):
                ht = Tensor(h, stop_gradient=True)
                with no_grad_guard():
                    pj = {n: local_params[j][n][0]
                          for n in local_params[j]}
                    from ....nn.layer.layers import functional_state
                    with functional_state(tmpl, pj, {}):
                        out = tmpl(ht)._data
                h = jnp.where(local_gates[0, j], out, h)
            return h
        if recompute:
            return jax.checkpoint(apply)(state)
        return apply(state)

    def pipe_fn(local_params, local_gates, xm):
        stage = jax.lax.axis_index(axis_name)
        zero = jnp.zeros_like(xm[0])
        fwd_perm = [(i, i + 1) for i in range(P - 1)]

        def tick(state, t):
            recv = jax.lax.ppermute(state, axis_name, fwd_perm) \
                if P > 1 else state
            inject = jax.lax.dynamic_index_in_dim(
                xm, jnp.minimum(t, M - 1), keepdims=False)
            inject = jnp.where(t < M, inject, zero)
            state = jnp.where(stage == 0, inject, recv)
            state = stage_apply(local_params, local_gates, state)
            out = jnp.where(stage == P - 1, state, zero)
            return state, out

        _, ys = jax.lax.scan(tick, zero, jnp.arange(M + P - 1))
        y = ys[P - 1:]
        # broadcast last stage's outputs to every pipe rank
        return jax.lax.psum(y, axis_name) if P > 1 else y

    in_specs = (
        [{n: PS(axis_name) for n in layer_p} for layer_p in stacked_params],
        PS(axis_name),
        PS(),
    )
    # partial-manual shard_map: only "pipe" goes manual, every other mesh
    # axis (data/model/sharding/...) stays under GSPMD inside the stages
    fn = jax.shard_map(pipe_fn, mesh=mesh, in_specs=in_specs,
                       out_specs=PS(), axis_names=frozenset({axis_name}),
                       check_vma=False)
    return fn(stacked_params, gates, x_microbatches)


class PipelineParallel(Layer):
    """Wraps (embed, PipelineLayer trunk, head) for sharded execution.

    ``train_batch(data, optimizer, scaler)`` mirrors the reference API
    (pipeline_parallel.py:train_batch): splits the batch into
    ``accumulate_steps`` micro-batches, runs the pipelined step, returns
    the mean loss.

    Tied embed/head: pass layers sharing Parameter OBJECTS (e.g. a head
    whose matmul reads the embedding weight). Shared leaves are aliased to
    one optimizer entry; jax sums the gradient contributions — the
    reference's SharedLayerDesc grad-allreduce, without the comm op.
    """

    def __init__(self, layers, hcg=None, strategy=None, embed=None,
                 head=None, loss_fn=None, num_microbatches=None,
                 recompute=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self.trunk = layers
        self.embed = embed
        self.head = head
        self._loss_fn = loss_fn or getattr(layers, "_loss_fn", None)
        self._hcg = hcg
        self._strategy = strategy
        self.num_microbatches = num_microbatches or (
            strategy.pipeline_configs.accumulate_steps if strategy else 1)
        # default True: recompute is what delivers the 1F1B-parity O(P)
        # activation-memory bound (module docstring); pass recompute=False
        # explicitly for small models where storing residuals is faster.
        # (strategy.recompute defaults False as a GENERAL training knob —
        # it must not silently strip the pipeline's memory bound.)
        self.recompute = True if recompute is None else bool(recompute)
        self._engine = None
        self._templates = None
        self._stacked = None

    def forward(self, x):
        """Sequential (non-pipelined) reference path."""
        if self.embed is not None:
            x = self.embed(x)
        x = self.trunk(x)
        if self.head is not None:
            x = self.head(x)
        return x

    # -- sharded pipelined step -------------------------------------------
    def _collect_aux(self):
        """Aux (embed/head) params with shared-object aliasing: a tied
        weight appears ONCE in the flat dict; both users read it through
        the alias map."""
        aux_params = {}
        alias = {}
        by_id = {}
        for part, prefix in ((self.embed, "embed"), (self.head, "head")):
            if part is None:
                continue
            for n, p in part.named_parameters():
                key = id(p)
                if key in by_id:
                    alias[f"{prefix}.{n}"] = by_id[key]
                else:
                    canonical = f"{prefix}.{n}"
                    by_id[key] = canonical
                    aux_params[canonical] = p._data
                    alias[f"{prefix}.{n}"] = canonical
        return aux_params, alias

    def _apply_aux(self, part, prefix, aux_p, alias, x):
        from ....nn.layer.layers import functional_state
        pdict = {n: aux_p[alias[f"{prefix}.{n}"]]
                 for n, _ in part.named_parameters()}
        with functional_state(part, pdict, {}):
            return part(x)

    def _build_step(self, optimizer):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as PS

        mesh = (self._hcg.mesh if self._hcg is not None
                else _env.get_mesh())
        P = self.trunk.num_stages
        M = self.num_microbatches
        templates, stacked, gates = _stack_stage_params(self.trunk)
        self._templates, self._stacked = templates, stacked
        aux_params, alias = self._collect_aux()
        loss_fn = self._loss_fn
        recompute = self.recompute

        def step(stacked_params, aux, opt_state, batch, labels, lr):
            def loss_of(trees):
                sp, aux_p = trees
                x = Tensor(batch, stop_gradient=True)
                with no_grad_guard():
                    if self.embed is not None:
                        x = self._apply_aux(self.embed, "embed", aux_p,
                                            alias, x)
                h = x._data
                mb = h.shape[0] // M
                xm = h.reshape((M, mb) + h.shape[1:])
                ym = pipeline_forward(templates, sp, xm, mesh, P,
                                      recompute=recompute, gates=gates)
                y = ym.reshape((M * mb,) + ym.shape[2:])
                out = Tensor(y, stop_gradient=True)
                with no_grad_guard():
                    if self.head is not None:
                        out = self._apply_aux(self.head, "head", aux_p,
                                              alias, out)
                    loss = loss_fn(out, Tensor(labels))
                lv = loss._data
                return (jnp.mean(lv) if lv.ndim else lv).astype(jnp.float32)

            loss, (g_stacked, g_aux) = jax.value_and_grad(loss_of)(
                (stacked_params, aux))
            flat_params = {}
            flat_grads = {}
            for j, layer_p in enumerate(stacked_params):
                for n, v in layer_p.items():
                    flat_params[f"t{j}.{n}"] = v
                    flat_grads[f"t{j}.{n}"] = g_stacked[j][n]
            flat_params.update(aux)
            flat_grads.update(g_aux)
            new_flat, new_opt = optimizer.apply_gradients(
                flat_params, flat_grads, opt_state, lr)
            new_stacked = [
                {n: new_flat[f"t{j}.{n}"] for n in layer_p}
                for j, layer_p in enumerate(stacked_params)]
            new_aux = {n: new_flat[n] for n in aux}
            return new_stacked, new_aux, new_opt, loss

        # shardings: trunk stacked on pipe; aux replicated
        pipe_sh = NamedSharding(mesh, PS("pipe"))
        rep = NamedSharding(mesh, PS())
        stacked_dev = [
            {n: jax.device_put(v, pipe_sh) for n, v in lp.items()}
            for lp in stacked]
        aux_dev = {n: jax.device_put(v, rep) for n, v in aux_params.items()}
        flat0 = {}
        for j, lp in enumerate(stacked_dev):
            for n, v in lp.items():
                flat0[f"t{j}.{n}"] = v
        flat0.update(aux_dev)
        opt_state = optimizer.init_state(flat0)
        self._state = (stacked_dev, aux_dev, opt_state)
        self._step = jax.jit(step)
        self._mesh = mesh

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """One pipelined training step. data = [inputs, labels]."""
        import jax.numpy as jnp
        inner = getattr(optimizer, "_inner", optimizer)
        if self._engine is None:
            self._build_step(inner)
            self._engine = True
        x, labels = data
        x = np.asarray(x)
        labels = np.asarray(labels)
        stacked, aux, opt_state = self._state
        lr = jnp.asarray(inner.get_lr(), jnp.float32)
        with self._mesh:
            stacked, aux, opt_state, loss = self._step(
                stacked, aux, opt_state, x, labels, lr)
        self._state = (stacked, aux, opt_state)
        return Tensor(loss)

    def sync_to_layers(self):
        """Copy trained stacked/aux params back into the Layer objects."""
        import jax
        stacked, aux, _ = self._state
        Pn = self.trunk.num_stages
        for s in range(Pn):
            for j, layer in enumerate(self.trunk.get_stage_layers(s)):
                for n, p in layer.named_parameters():
                    p._data = jax.device_get(stacked[j][n])[s]
        _, alias = self._collect_aux()
        seen = set()
        for part, prefix in ((self.embed, "embed"), (self.head, "head")):
            if part is not None:
                for n, p in part.named_parameters():
                    canonical = alias[f"{prefix}.{n}"]
                    if canonical in seen:
                        continue  # tied weight: one write is the truth
                    seen.add(canonical)
                    p._data = jax.device_get(aux[canonical])
