"""``paddle.distributed.utils`` (reference: python/paddle/distributed/
utils.py — the launcher-era cluster/pod/trainer helpers plus the MoE
``global_scatter``/``global_gather`` collectives).

The cluster bookkeeping classes are real (the elastic launcher uses the
same shapes); process management wraps the spawn machinery. The MoE
collectives map to the expert-parallel all_to_all the reference built
them for (incubate/moe.py owns the jitted path; these are the eager
count-driven forms).
"""
from __future__ import annotations

import logging
import os
import signal
import socket
import subprocess
import time
from contextlib import closing
from typing import List, Optional

import numpy as np

__all__ = ["get_host_name_ip", "get_cluster", "get_logger",
           "find_free_ports", "add_arguments", "terminate_local_procs",
           "start_local_trainers", "watch_local_trainers",
           "pull_worker_log", "global_scatter", "global_gather",
           "Cluster", "Pod", "Trainer", "TrainerProc", "JobServer",
           "Hdfs"]


def get_logger(log_level=20, name="root"):
    logger = logging.getLogger(name)
    logger.setLevel(log_level)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s-%(levelname)s: %(message)s"))
        logger.addHandler(h)
    return logger


def get_host_name_ip():
    try:
        host = socket.gethostname()
        return host, socket.gethostbyname(socket.getfqdn(host))
    except OSError:
        return None


def find_free_ports(num: int):
    ports = set()
    for _ in range(num * 10):
        if len(ports) >= num:
            break
        with closing(socket.socket(socket.AF_INET,
                                   socket.SOCK_STREAM)) as s:
            s.bind(("", 0))
            ports.add(s.getsockname()[1])
    return ports if len(ports) >= num else None


def add_arguments(argname, type, default, help, argparser):  # noqa: A002
    """Reference utils.add_arguments (argparse helper)."""
    argparser.add_argument("--" + argname, default=default, type=type,
                           help=f"{help} Default: %(default)s.")


# --------------------------------------------------------------------------
# cluster bookkeeping (reference utils.py Cluster/Pod/Trainer/...)
# --------------------------------------------------------------------------

class Trainer:
    def __init__(self):
        self.gpus: List[int] = []
        self.endpoint: Optional[str] = None
        self.rank: Optional[int] = None

    def __eq__(self, other):
        return (self.gpus, self.endpoint, self.rank) == \
            (other.gpus, other.endpoint, other.rank)

    def __ne__(self, other):
        return not self.__eq__(other)


class Pod:
    def __init__(self):
        self.rank: Optional[int] = None
        self.id: Optional[str] = None
        self.addr: Optional[str] = None
        self.port: Optional[int] = None
        self.trainers: List[Trainer] = []
        self.gpus: List[int] = []

    def rank_of(self, trainer) -> int:
        try:
            return self.trainers.index(trainer)
        except ValueError:
            return -1


class Cluster:
    def __init__(self, hdfs=None):
        self.job_server = None
        self.pods: List[Pod] = []
        self.hdfs = hdfs

    def trainers_nranks(self) -> int:
        return len(self.trainers_endpoints())

    def trainers_endpoints(self) -> List[str]:
        return [t.endpoint for pod in self.pods for t in pod.trainers]

    def pods_endpoints(self) -> List[str]:
        return [f"{p.addr}:{p.port}" for p in self.pods]

    def get_pod_by_id(self, pod_id):
        for p in self.pods:
            if p.id == pod_id:
                return p
        return None


class JobServer:
    def __init__(self):
        self.endpoint: Optional[str] = None


class Hdfs:
    def __init__(self):
        self.hdfs_ugi = None
        self.hdfs_name = None
        self.hdfs_path = None

    def is_valid(self):
        return all((self.hdfs_ugi, self.hdfs_name, self.hdfs_path))


class TrainerProc:
    def __init__(self):
        self.proc = None
        self.log_fn = None
        self.log_offset = 0
        self.rank = None
        self.local_rank = None
        self.cmd = None


def get_cluster(node_ips, node_ip, trainer_endpoints, device_mode=None,
                devices_per_proc=None):
    """Assemble a Cluster from endpoint lists (reference get_cluster)."""
    cluster = Cluster()
    rank = 0
    for pod_rank, ip in enumerate(node_ips):
        pod = Pod()
        pod.rank = pod_rank
        pod.addr = ip
        pod.id = str(pod_rank)
        eps = trainer_endpoints[pod_rank] \
            if trainer_endpoints and isinstance(trainer_endpoints[0],
                                                (list, tuple)) \
            else [e for e in (trainer_endpoints or [])
                  if e.split(":")[0] == ip]
        for ep in eps:
            t = Trainer()
            t.endpoint = ep
            t.rank = rank
            rank += 1
            pod.trainers.append(t)
        cluster.pods.append(pod)
    return cluster, cluster.get_pod_by_id(str(node_ips.index(node_ip)))


def start_local_trainers(cluster, pod, training_script,
                         training_script_args, log_dir=None, envs=None):
    """Spawn one process per trainer in ``pod`` with PADDLE_* env wiring
    (reference start_local_trainers; the launch module owns the richer
    restart/elastic path)."""
    procs = []
    eps = cluster.trainers_endpoints()
    for local_rank, t in enumerate(pod.trainers):
        env = dict(os.environ, **(envs or {}))
        env.update({
            "PADDLE_TRAINER_ID": str(t.rank),
            "PADDLE_TRAINERS_NUM": str(cluster.trainers_nranks()),
            "PADDLE_CURRENT_ENDPOINT": t.endpoint or "",
            "PADDLE_TRAINER_ENDPOINTS": ",".join(e or "" for e in eps),
        })
        log_fn = None
        stdout = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            log_fn = open(os.path.join(log_dir,
                                       f"workerlog.{local_rank}"), "w")
            stdout = log_fn
        import sys
        proc = subprocess.Popen(
            [sys.executable, "-u", training_script,
             *training_script_args],
            env=env, stdout=stdout, stderr=stdout)
        tp = TrainerProc()
        tp.proc = proc
        tp.rank = t.rank
        tp.local_rank = local_rank
        tp.log_fn = log_fn
        procs.append(tp)
    return procs


def watch_local_trainers(procs, nranks):
    """Poll once: return alive procs; raise if any died nonzero
    (reference watch_local_trainers semantics, sans the global abort)."""
    alive = []
    for tp in procs:
        rc = tp.proc.poll()
        if rc is None:
            alive.append(tp)
        elif rc != 0:
            terminate_local_procs(procs)
            raise RuntimeError(
                f"trainer rank {tp.rank} exited with code {rc}")
    return alive


def terminate_local_procs(procs):
    for tp in procs:
        if tp.proc is not None and tp.proc.poll() is None:
            tp.proc.terminate()
    deadline = time.time() + 10
    for tp in procs:
        if tp.proc is None:
            continue
        try:
            tp.proc.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            tp.proc.send_signal(signal.SIGKILL)
        if tp.log_fn:
            tp.log_fn.close()


def pull_worker_log(tp) -> None:
    """Stream new bytes of a trainer's log to stdout (reference
    pull_worker_log)."""
    if tp.log_fn is None:
        return
    with open(tp.log_fn.name, "rb") as f:
        f.seek(tp.log_offset)
        data = f.read()
        tp.log_offset = f.tell()
    if data:
        print(data.decode(errors="replace"), end="")


# --------------------------------------------------------------------------
# MoE count-driven collectives (reference utils.py global_scatter/
# global_gather over alltoall; incubate/moe.py owns the jitted dispatch)
# --------------------------------------------------------------------------

def _counts_np(v):
    from ..framework.tensor import Tensor
    return np.asarray(v.numpy() if isinstance(v, Tensor) else v,
                      np.int64)


def global_scatter(x, local_count, global_count, group=None,
                   use_calc_stream=True):
    """Reorganize rows of ``x`` from expert-major-local to the layout
    each expert receives (reference global_scatter). Single-process
    form: with world size 1 the alltoall is an identity over the local
    counts, so x passes through partitioned by ``local_count``."""
    from . import get_world_size
    if get_world_size() > 1:
        raise NotImplementedError(
            "multi-process global_scatter is served by the jitted "
            "expert-parallel dispatch (incubate.moe, all_to_all over "
            "the mesh); the eager count-driven form is single-process")
    return x


def global_gather(x, local_count, global_count, group=None,
                  use_calc_stream=True):
    """Inverse of global_scatter (reference global_gather)."""
    from . import get_world_size
    if get_world_size() > 1:
        raise NotImplementedError(
            "multi-process global_gather is served by the jitted "
            "expert-parallel combine (incubate.moe)")
    return x
