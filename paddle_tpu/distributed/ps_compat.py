"""Remaining ``paddle.distributed`` surface: mp ``split``, ParallelMode,
gloo facades, and the PS-side dataset/entry configs.

Reference: python/paddle/distributed/collective.py:1557 (split — weight
sharding for embedding/linear over model-parallel groups),
parallel.py (ParallelMode, gloo_*), fleet/dataset/ (InMemoryDataset /
QueueDataset feeding the CTR trainers), entry.py (sparse-table
admission configs).

TPU-native mapping: ``split`` builds the GSPMD-sharded parallel layer
(mp_layers.py) instead of hand-slicing weights per rank — the mesh
partitioner emits the collectives the reference's c_split/c_concat ops
perform. The gloo_* trio fronts the coordination-service bootstrap (we
have no gloo; the XLA distributed runtime is the CPU-side rendezvous).
The dataset classes are REAL host-side loaders (files -> in-memory
sample list with shuffle/batch iteration); the *Entry configs attach to
``distributed.embedding.ShardedEmbedding`` frequency tracking rather
than a brpc sparse table (see README.md scope decision).
"""
from __future__ import annotations

import os
import warnings
from typing import Dict, List, Sequence

import numpy as np

__all__ = ["ParallelMode", "split", "gloo_init_parallel_env",
           "gloo_barrier", "gloo_release", "InMemoryDataset",
           "QueueDataset", "CountFilterEntry", "ProbabilityEntry",
           "ShowClickEntry"]


class ParallelMode:
    """Reference parallel.ParallelMode constants."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


# split() call-site layer cache: the reference registers the split
# weights in the program; here the layer persists across calls so
# (a) repeated calls reuse ONE weight (stable outputs, trainable) and
# (b) static capture records the Parameters into the program, where
# minimize()/state_dict reach them. Keyed by name= or the config.
_SPLIT_LAYERS: Dict[tuple, object] = {}


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Weight-sharded embedding/linear (reference collective.py:1557).

    The reference hand-splits the weight across ``num_partitions`` ranks
    and wires c_allreduce/c_concat; here the parallel layer annotates the
    sharding and GSPMD partitions the op over the mesh's "model" axis —
    ``num_partitions`` must match that axis when a mesh is active.
    The created layer (and its parameters) is cached per ``name=`` (or
    per config) — pass distinct names for distinct split weights."""
    from . import env as _env
    from .fleet.meta_parallel.parallel_layers.mp_layers import (
        ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    )
    mesh = _env.get_mesh()
    if mesh is not None and "model" in mesh.shape and \
            mesh.shape["model"] not in (1, num_partitions):
        raise ValueError(
            f"num_partitions={num_partitions} does not match the mesh's "
            f"model axis ({mesh.shape['model']})")
    key = (name,) if name else (operation, tuple(size), axis,
                                gather_out, bias_attr is not False)
    layer = _SPLIT_LAYERS.get(key)
    if layer is None:
        if operation == "embedding":
            layer = VocabParallelEmbedding(size[0], size[1],
                                           weight_attr=weight_attr)
        elif operation == "linear":
            if axis == 0:
                # weight split along in_features rows -> partial matmuls
                layer = RowParallelLinear(size[0], size[1],
                                          weight_attr=weight_attr,
                                          has_bias=bias_attr is not False)
            elif axis == 1:
                layer = ColumnParallelLinear(
                    size[0], size[1], weight_attr=weight_attr,
                    has_bias=bias_attr is not False,
                    gather_output=gather_out)
            else:
                raise ValueError("linear split axis must be 0 or 1")
        else:
            raise ValueError(f"unsupported split operation {operation!r} "
                             f"(embedding | linear)")
        _SPLIT_LAYERS[key] = layer
    return layer(x)


def split_layer(name=None, **config):
    """The cached layer a prior ``split`` call created (its parameters
    live here; reference code reaches them through the program)."""
    key = (name,) if name else (config["operation"],
                                tuple(config["size"]),
                                config.get("axis", 0),
                                config.get("gather_out", True),
                                config.get("bias_attr") is not False)
    return _SPLIT_LAYERS.get(key)


# --------------------------------------------------------------------------
# gloo facades: the reference uses gloo for CPU barrier/rendezvous in PS
# and data-parallel CPU mode; the coordination service plays that role
# --------------------------------------------------------------------------

def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """Bootstrap the CPU-side rendezvous (reference gloo store init)."""
    from . import env as _env
    if _env.is_initialized():
        return
    _env.init_parallel_env(coordinator_address=server_endpoint,
                           num_processes=int(rank_num),
                           process_id=int(rank_id))


def gloo_barrier():
    from . import env as _env
    if not _env.is_initialized():
        warnings.warn("gloo_barrier before gloo_init_parallel_env is a "
                      "no-op", UserWarning, stacklevel=2)
        return
    from .collective import barrier
    barrier()


def gloo_release():
    """The coordination service tears down at process exit; nothing to
    hold (reference frees the gloo store here)."""


# --------------------------------------------------------------------------
# CTR dataset loaders (reference fleet/dataset/dataset.py) — real
# host-side file ingestion; the MPI/brpc distribution legs are descoped
# --------------------------------------------------------------------------

class _FileDatasetBase:
    def __init__(self):
        self._filelist: List[str] = []
        self._parse = self._default_parse
        self._batch_size = 1
        self._thread = 1

    # reference init(...) knobs — recorded; pipe_command replaced by a
    # python parse_fn (no subprocess pipeline on the TPU host path)
    def init(self, batch_size=1, thread_num=1, pipe_command=None,
             parse_fn=None, use_var=None, **kwargs):
        if int(batch_size) < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self._batch_size = int(batch_size)
        self._thread = int(thread_num)
        if pipe_command is not None and parse_fn is None:
            warnings.warn(
                "pipe_command subprocess parsing is not supported; pass "
                "parse_fn=callable(line)->sample instead",
                UserWarning, stacklevel=2)
        if parse_fn is not None:
            self._parse = parse_fn
        return self

    @staticmethod
    def _default_parse(line: str):
        return np.asarray([float(v) for v in line.split()], np.float32)

    def set_filelist(self, filelist: Sequence[str]):
        missing = [f for f in filelist if not os.path.exists(f)]
        if missing:
            raise FileNotFoundError(f"dataset files not found: {missing}")
        self._filelist = list(filelist)

    def set_batch_size(self, batch_size):
        if int(batch_size) < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self._batch_size = int(batch_size)

    @staticmethod
    def _stack_or_list(batch):
        # ragged samples cannot stack: hand the list to the caller (same
        # tolerance in both dataset variants)
        try:
            return np.stack(batch)
        except ValueError:
            return batch

    def _iter_lines(self):
        for path in self._filelist:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield line


class InMemoryDataset(_FileDatasetBase):
    """Loads every sample into host memory; shuffle + batch iteration
    (reference InMemoryDataset.load_into_memory/local_shuffle)."""

    def __init__(self):
        super().__init__()
        self._samples: List[np.ndarray] = []

    def load_into_memory(self):
        self._samples = [self._parse(ln) for ln in self._iter_lines()]

    def local_shuffle(self, seed=None):
        rng = np.random.RandomState(seed)
        rng.shuffle(self._samples)

    def global_shuffle(self, fleet=None, thread_num=None, seed=None):
        # single-host: global == local (multi-host PS shuffle descoped)
        self.local_shuffle(seed)

    def get_memory_data_size(self, fleet=None) -> int:
        return len(self._samples)

    def release_memory(self):
        self._samples = []

    def __iter__(self):
        for i in range(0, len(self._samples), self._batch_size):
            yield self._stack_or_list(self._samples[i:i + self._batch_size])

    def __len__(self):
        return (len(self._samples) + self._batch_size - 1) // \
            max(1, self._batch_size)


class QueueDataset(_FileDatasetBase):
    """Streaming variant: one pass over the files, nothing resident
    (reference QueueDataset)."""

    def __iter__(self):
        batch: List[np.ndarray] = []
        for ln in self._iter_lines():
            batch.append(self._parse(ln))
            if len(batch) == self._batch_size:
                yield self._stack_or_list(batch)
                batch = []
        if batch:
            yield self._stack_or_list(batch)


# --------------------------------------------------------------------------
# sparse-table admission configs (reference distributed/entry_attr.py):
# plain config records; on this backend they document/drive the offline
# admission pass over ShardedEmbedding.frequency() counters
# --------------------------------------------------------------------------

class CountFilterEntry:
    """Admit a feature row only after >= count hits."""

    def __init__(self, count: int):
        if count < 0:
            raise ValueError("count must be >= 0")
        self.count = int(count)

    def admit(self, frequency: np.ndarray) -> np.ndarray:
        """Row mask over a ShardedEmbedding frequency vector."""
        return np.asarray(frequency) >= self.count

    def __repr__(self):
        return f"count_filter_entry:{self.count}"


class ProbabilityEntry:
    """Admit a new feature row with the given probability."""

    def __init__(self, probability: float):
        if not 0.0 <= probability <= 1.0:
            raise ValueError("probability must be in [0, 1]")
        self.probability = float(probability)

    def admit(self, frequency: np.ndarray, seed=None) -> np.ndarray:
        rng = np.random.RandomState(seed)
        return rng.rand(len(frequency)) < self.probability

    def __repr__(self):
        return f"probability_entry:{self.probability}"


class ShowClickEntry:
    """Names the show/click stat vars feeding CTR-weighted admission."""

    def __init__(self, show_name: str, click_name: str):
        self.show_name = str(show_name)
        self.click_name = str(click_name)

    def __repr__(self):
        return f"show_click_entry:{self.show_name}:{self.click_name}"
