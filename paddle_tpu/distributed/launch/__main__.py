from . import main

main()
