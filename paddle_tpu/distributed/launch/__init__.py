"""``python -m paddle_tpu.distributed.launch`` — multi-process job launcher.

Reference: python/paddle/distributed/launch/main.py:18 (Context → controller
→ Job/Pod/Container spawn + watch), fleet/elastic/manager.py:131 (restart
policy, exit-code-101 restart signal), launch/controllers/watcher.py.

TPU-native shape: the reference spawns ONE process per GPU; under jax one
process drives all local chips, so the natural unit is one process per
host (``--nproc_per_node`` stays available for CPU-mesh testing and
multi-plane hosts). The launcher wires the PADDLE_* env that
``env.init_parallel_env`` already reads — PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM / PADDLE_MASTER — so the rendezvous is jax's PjRt
coordination service instead of a TCPStore. Elastic policy: a child that
exits with code 101 (the reference's restart signal) or any non-zero code
triggers a full local respawn up to ``--max_restarts`` times; rank logs
stream to ``--log_dir``.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["main", "launch_local"]

# the reference's elastic manager treats 101 as "please restart me"
# (fleet/elastic/manager.py ELASTIC_AUTO_PARALLEL_EXIT_CODE area)
RESTART_EXIT_CODE = 101


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _parse(argv: Optional[List[str]] = None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch a multi-process distributed job "
                    "(reference: paddle.distributed.launch)")
    p.add_argument("--nnodes", type=int, default=1,
                   help="number of hosts in the job")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")),
                   help="this host's index")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per host (1 = jax-native: one process "
                        "drives all local chips)")
    p.add_argument("--master", type=str,
                   default=os.environ.get("PADDLE_MASTER"),
                   help="coordinator ip:port (default: auto on "
                        "single-host)")
    p.add_argument("--log_dir", type=str, default=None,
                   help="write per-rank logs here instead of inheriting "
                        "stdio")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="elastic: respawn the local pod up to N times on "
                        "child failure")
    p.add_argument("--backend", type=str, default=None,
                   help="override JAX_PLATFORMS for children (e.g. cpu "
                        "for mesh tests)")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


class _Pod:
    """The local process group (reference launch/job/pod.py Container
    set)."""

    def __init__(self, args):
        self.args = args
        self.procs: List[subprocess.Popen] = []
        self.logs = []

    def spawn(self):
        a = self.args
        world = a.nnodes * a.nproc_per_node
        master = a.master
        if master is None:
            if a.nnodes > 1:
                raise SystemExit(
                    "--master ip:port is required for multi-host jobs")
            master = f"127.0.0.1:{_free_port()}"
        for local in range(a.nproc_per_node):
            rank = a.node_rank * a.nproc_per_node + local
            env = dict(os.environ)
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_MASTER": master,
                "PADDLE_LOCAL_RANK": str(local),
                "PADDLE_NNODES": str(a.nnodes),
            })
            if a.backend:
                env["JAX_PLATFORMS"] = a.backend
            cmd = [sys.executable, a.training_script,
                   *a.training_script_args]
            if a.log_dir:
                os.makedirs(a.log_dir, exist_ok=True)
                logf = open(os.path.join(
                    a.log_dir, f"workerlog.{rank}"), "ab")
                self.logs.append(logf)
                proc = subprocess.Popen(cmd, env=env, stdout=logf,
                                        stderr=subprocess.STDOUT)
            else:
                proc = subprocess.Popen(cmd, env=env)
            self.procs.append(proc)

    def poll(self):
        """Returns None while running, else the pod's exit code (first
        failure wins; 0 when all exited cleanly)."""
        codes = [p.poll() for p in self.procs]
        for c in codes:
            if c is not None and c != 0:
                return c
        if all(c == 0 for c in codes):
            return 0
        return None

    def terminate(self):
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 10
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        for f in self.logs:
            f.close()
        self.procs, self.logs = [], []


def launch_local(argv: Optional[List[str]] = None) -> int:
    """Spawn + watch + elastic-restart loop. Returns the job exit code."""
    args = _parse(argv)
    restarts = 0
    while True:
        pod = _Pod(args)
        pod.spawn()
        try:
            while True:
                code = pod.poll()
                if code is not None:
                    break
                time.sleep(0.2)
        except KeyboardInterrupt:
            pod.terminate()
            return 130
        # terminate() also closes/flushes the workerlog handles, so run
        # it on EVERY exit path (clean exit included)
        pod.terminate()
        if code == 0:
            return 0
        if restarts < args.max_restarts:
            restarts += 1
            print(f"[launch] child failed with code {code}; elastic "
                  f"restart {restarts}/{args.max_restarts}",
                  file=sys.stderr, flush=True)
            continue
        return int(code)


def main():
    raise SystemExit(launch_local())
