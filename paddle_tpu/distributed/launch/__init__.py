"""``python -m paddle_tpu.distributed.launch`` — multi-process job launcher.

Reference: python/paddle/distributed/launch/main.py:18 (Context → controller
→ Job/Pod/Container spawn + watch), fleet/elastic/manager.py:131 (restart
policy, exit-code-101 restart signal), launch/controllers/watcher.py.

TPU-native shape: the reference spawns ONE process per GPU; under jax one
process drives all local chips, so the natural unit is one process per
host (``--nproc_per_node`` stays available for CPU-mesh testing and
multi-plane hosts). The launcher wires the PADDLE_* env that
``env.init_parallel_env`` already reads — PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM / PADDLE_MASTER — so the rendezvous is jax's PjRt
coordination service instead of a TCPStore. Elastic policy: a child that
exits with code 101 (the reference's restart signal) or any non-zero code
triggers a full local respawn up to ``--max_restarts`` times; rank logs
stream to ``--log_dir``.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["main", "launch_local"]

# the reference's elastic manager treats 101 as "please restart me"
# (fleet/elastic/manager.py ELASTIC_AUTO_PARALLEL_EXIT_CODE area)
RESTART_EXIT_CODE = 101


from ..elastic import _free_port  # shared bind-port-0 helper  # noqa: E402


def _parse(argv: Optional[List[str]] = None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch a multi-process distributed job "
                    "(reference: paddle.distributed.launch)")
    p.add_argument("--nnodes", type=int, default=1,
                   help="number of hosts in the job")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")),
                   help="this host's index")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per host (1 = jax-native: one process "
                        "drives all local chips)")
    p.add_argument("--master", type=str,
                   default=os.environ.get("PADDLE_MASTER"),
                   help="coordinator ip:port (default: auto on "
                        "single-host)")
    p.add_argument("--log_dir", type=str, default=None,
                   help="write per-rank logs here instead of inheriting "
                        "stdio")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="elastic: respawn the local pod up to N times on "
                        "child failure")
    p.add_argument("--elastic_master", type=str, default=None,
                   help="elastic membership master host:port "
                        "(node_rank 0 hosts it); enables heartbeat "
                        "membership + rebuild-on-node-change")
    p.add_argument("--elastic_ttl", type=float, default=6.0,
                   help="seconds without heartbeats before a node is "
                        "declared dead")
    p.add_argument("--backend", type=str, default=None,
                   help="override JAX_PLATFORMS for children (e.g. cpu "
                        "for mesh tests)")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


class _Pod:
    """The local process group (reference launch/job/pod.py Container
    set). ``membership`` (elastic) overrides nnodes/node_rank/master with
    the current alive-node view."""

    def __init__(self, args, membership=None):
        self.args = args
        self.membership = membership
        self.procs: List[subprocess.Popen] = []
        self.logs = []

    def spawn(self):
        a = self.args
        nnodes, node_rank, master = a.nnodes, a.node_rank, a.master
        if self.membership is not None:
            nnodes, node_rank, master = self.membership
        world = nnodes * a.nproc_per_node
        if master is None:
            if nnodes > 1:
                raise SystemExit(
                    "--master ip:port is required for multi-host jobs")
            master = f"127.0.0.1:{_free_port()}"
        for local in range(a.nproc_per_node):
            rank = node_rank * a.nproc_per_node + local
            env = dict(os.environ)
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_MASTER": master,
                "PADDLE_LOCAL_RANK": str(local),
                "PADDLE_NNODES": str(nnodes),
            })
            if a.backend:
                env["JAX_PLATFORMS"] = a.backend
            cmd = [sys.executable, a.training_script,
                   *a.training_script_args]
            if a.log_dir:
                os.makedirs(a.log_dir, exist_ok=True)
                logf = open(os.path.join(
                    a.log_dir, f"workerlog.{rank}"), "ab")
                self.logs.append(logf)
                proc = subprocess.Popen(cmd, env=env, stdout=logf,
                                        stderr=subprocess.STDOUT)
            else:
                proc = subprocess.Popen(cmd, env=env)
            self.procs.append(proc)

    def poll(self):
        """Returns None while running, else the pod's exit code (first
        failure wins; 0 when all exited cleanly)."""
        codes = [p.poll() for p in self.procs]
        for c in codes:
            if c is not None and c != 0:
                return c
        if all(c == 0 for c in codes):
            return 0
        return None

    def terminate(self):
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 10
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        for f in self.logs:
            f.close()
        self.procs, self.logs = [], []


def launch_local(argv: Optional[List[str]] = None) -> int:
    """Spawn + watch + elastic-restart loop. Returns the job exit code."""
    args = _parse(argv)
    if args.elastic_master:
        return _launch_elastic(args)
    restarts = 0
    while True:
        pod = _Pod(args)
        pod.spawn()
        try:
            while True:
                code = pod.poll()
                if code is not None:
                    break
                time.sleep(0.2)
        except KeyboardInterrupt:
            pod.terminate()
            return 130
        # terminate() also closes/flushes the workerlog handles, so run
        # it on EVERY exit path (clean exit included)
        pod.terminate()
        if code == 0:
            return 0
        if restarts < args.max_restarts:
            restarts += 1
            print(f"[launch] child failed with code {code}; elastic "
                  f"restart {restarts}/{args.max_restarts}",
                  file=sys.stderr, flush=True)
            continue
        return int(code)


def _launch_elastic(args) -> int:
    """Membership-driven watch loop (reference:
    fleet/elastic/manager.py): register with the master, heartbeat,
    rebuild the pod whenever the alive-node set changes — ranks and world
    size rewritten from the sorted node list, a fresh PjRt port per
    membership version. Node_rank 0 hosts the master in-process (the
    documented single-master trade-off vs the reference's external ETCD).
    """
    from ..elastic import ElasticAgent, ElasticMaster, sort_nodes

    host, port = args.elastic_master.rsplit(":", 1)
    master = None
    if args.node_rank == 0:
        master = ElasticMaster(int(port), ttl=args.elastic_ttl)
    node_id = f"{host if args.node_rank == 0 else socket.gethostname()}" \
              f"#{args.node_rank}"
    agent = ElasticAgent(args.elastic_master, node_id)
    agent.register()
    agent.start_heartbeat()
    restarts = 0
    code = 1
    # a peer whose master is gone for this long gives up instead of
    # spinning forever (the single-master fate-sharing boundary)
    master_lost_after = max(3 * args.elastic_ttl, 30.0)
    try:
        while True:
            try:
                st = agent.status()
            except (OSError, ValueError):
                # transient blip at rebuild time: retry via register's
                # backoff rather than crashing the launcher
                st = agent.register()
            if agent.node_id not in st["nodes"]:
                st = agent.register()  # expired while rebuilding
            version = st["version"]
            # node_rank-suffix order, NOT lexicographic: the node hosting
            # the master (node_rank 0) must map to global rank 0 so the
            # PjRt coordinator binds on its own host
            nodes = sort_nodes(st["nodes"])
            membership = (len(nodes), nodes.index(agent.node_id),
                          f"{host}:{st['pjrt_port']}")
            print(f"[launch] elastic v{version}: {len(nodes)} node(s), "
                  f"this={membership[1]}", file=sys.stderr, flush=True)
            pod = _Pod(args, membership=membership)
            pod.spawn()
            rebuild = False
            master_lost_since = None
            try:
                while True:
                    code = pod.poll()
                    if code is not None:
                        break
                    try:
                        cur = agent.status()
                        master_lost_since = None
                    except (OSError, ValueError):
                        cur = None  # master briefly unreachable: keep on
                        now = time.time()
                        if master_lost_since is None:
                            master_lost_since = now
                        elif now - master_lost_since > master_lost_after:
                            print("[launch] elastic master unreachable "
                                  f"for {master_lost_after:.0f}s; "
                                  "terminating", file=sys.stderr,
                                  flush=True)
                            pod.terminate()
                            return 1
                    if cur is not None and cur["version"] != version:
                        # a node died (TTL lapse) or joined: rebuild with
                        # rewritten world size/endpoints
                        print("[launch] membership changed "
                              f"(v{version} -> v{cur['version']}); "
                              "rebuilding", file=sys.stderr, flush=True)
                        rebuild = True
                        break
                    time.sleep(0.3)
            except KeyboardInterrupt:
                pod.terminate()
                return 130
            pod.terminate()
            if rebuild:
                continue
            if code == 0:
                return 0
            if restarts < args.max_restarts:
                restarts += 1
                print(f"[launch] child failed with code {code}; elastic "
                      f"restart {restarts}/{args.max_restarts}",
                      file=sys.stderr, flush=True)
                continue
            return int(code)
    finally:
        agent.stop_heartbeat()
        if code == 0:
            # clean exit leaves the membership explicitly; a FAILED node
            # just stops heartbeating, so peers detect it through the TTL
            # sweep — the actual dead-rank path (reference: ETCD lease
            # expiry, manager.py:131)
            agent.leave()
        if master is not None and code == 0:
            # clean job end: wait briefly so peers can observe the leave
            time.sleep(0.5)
        if master is not None:
            master.shutdown()


def main():
    raise SystemExit(launch_local())
