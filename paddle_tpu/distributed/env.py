"""Distributed environment: process/mesh state.

TPU-native replacement for the reference's bootstrap machinery —
TCPStore rendezvous (paddle/fluid/distributed/store/tcp_store.cc),
``init_parallel_env`` (python/paddle/distributed/parallel.py:93), NCCL comm
bootstrap (platform/gen_comm_id_helper.cc): multi-host jax initialises
through the PjRt coordination service (``jax.distributed.initialize``), and
every "comm group" is an axis of one global device Mesh. Collectives are
then XLA ops over ICI/DCN — rings, ids and stores disappear.

Env vars honored (reference launcher parity): PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM map onto process index/count;
PADDLE_MASTER / PADDLE_TRAINER_ENDPOINTS give the coordinator address.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_state = threading.local()
_global = {"initialized": False, "mesh": None, "topology": None}


def _jax():
    import jax
    return jax


def init_parallel_env(coordinator_address: Optional[str] = None,
                      num_processes: Optional[int] = None,
                      process_id: Optional[int] = None):
    """Bring up multi-host jax if env asks for it; no-op single-host.

    Reference analog: distributed/parallel.py:93 init_parallel_env.
    """
    if _global["initialized"]:
        return
    coordinator = coordinator_address or os.environ.get("PADDLE_MASTER") \
        or os.environ.get("MASTER_ADDR")
    nproc = num_processes or int(
        os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    pid = process_id if process_id is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", "0"))
    if coordinator and nproc > 1:
        _jax().distributed.initialize(
            coordinator_address=coordinator,
            num_processes=nproc,
            process_id=pid)
    _global["initialized"] = True


def get_rank() -> int:
    """Global process index (reference: paddle.distributed.get_rank)."""
    try:
        return _jax().process_index()
    except Exception:
        return 0


def get_world_size() -> int:
    """Number of processes (NOT devices) — matches reference semantics
    where one trainer process drives one accelerator; under jax one
    process drives all local devices, so device-level parallel degree is
    ``device_count()``."""
    try:
        return _jax().process_count()
    except Exception:
        return 1


def device_count() -> int:
    return len(_jax().devices())


def is_initialized() -> bool:
    return _global["initialized"]


def reset() -> None:
    """Clear process-group state so init_parallel_env can run again
    (destroy_process_group calls this after jax.distributed.shutdown)."""
    _global["initialized"] = False
    _global["mesh"] = None
    _global["topology"] = None


# ---------------------------------------------------------------------------
# the global hybrid mesh
# ---------------------------------------------------------------------------

def build_mesh(axes: Dict[str, int], devices=None):
    """Create (and register globally) a Mesh from axis-name -> degree.

    Axis order follows the reference's HybridCommunicateGroup layout
    ["data","pipe","sharding","model"] extended with "sep"/"expert"
    (fleet/base/topology.py:55) — outer axes ride DCN, inner axes ICI.
    """
    from jax.sharding import Mesh
    jax = _jax()
    devices = list(devices if devices is not None else jax.devices())
    degrees = [max(1, int(d)) for d in axes.values()]
    total = int(np.prod(degrees))
    if total > len(devices):
        raise ValueError(
            f"mesh axes {axes} need {total} devices but only "
            f"{len(devices)} are visible")
    # A mesh smaller than the machine is legal (reference new_group over a
    # rank subset): take the leading devices.
    arr = np.array(devices[:total]).reshape(degrees)
    mesh = Mesh(arr, tuple(axes.keys()))
    _global["mesh"] = mesh
    return mesh


def get_mesh():
    return _global["mesh"]


def set_mesh(mesh):
    _global["mesh"] = mesh


def set_topology(topo):
    _global["topology"] = topo


def get_topology():
    return _global["topology"]
