"""Sharded distributed checkpointing: save on N shards, load on M.

Reference: python/paddle/distributed/auto_parallel/dist_saver.py (per-rank
shard files + metadata) and converter.py (slice/merge when the load-time
parallelism differs from save-time).

TPU-native: state lives as sharded ``jax.Array`` pytrees, so the save
format is orbax/tensorstore — each host writes exactly its addressable
shards, and restore RE-SHARDS to whatever sharding the loading mesh asks
for (the converter.py slice/merge machinery collapses into tensorstore
range reads). One code path covers save-on-8/load-on-1, ZeRO-3 →
replicated, dp mesh → dp×mp mesh, and multi-host jobs.
"""
from __future__ import annotations

import os
from typing import Any, Optional

__all__ = ["save_sharded", "load_sharded", "save_state_dict",
           "load_state_dict"]


def _ocp():
    import orbax.checkpoint as ocp
    return ocp


def save_sharded(state: Any, path: str) -> None:
    """Save a pytree of (possibly sharded) jax arrays. Every process in a
    multi-host job must call this collectively."""
    ocp = _ocp()
    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, state, force=True)


def load_sharded(path: str, template: Optional[Any] = None,
                 shardings: Optional[Any] = None) -> Any:
    """Restore a pytree saved by :func:`save_sharded`.

    ``template``: pytree of arrays or jax.ShapeDtypeStruct giving the
    target structure; ``shardings``: matching pytree of
    ``jax.sharding.Sharding`` — each leaf is restored DIRECTLY into that
    sharding regardless of how many shards wrote it (save on N, load on
    M). With neither, arrays restore fully replicated on host.
    """
    import jax
    ocp = _ocp()
    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        if template is None and shardings is None:
            return ckptr.restore(path)
        if template is None:
            template = jax.tree_util.tree_map(
                lambda _: None, shardings,
                is_leaf=lambda x: hasattr(x, "device_set"))

        def arg(t, s):
            if s is not None:
                return ocp.ArrayRestoreArgs(sharding=s)
            return ocp.RestoreArgs()

        if shardings is None:
            restore_args = jax.tree_util.tree_map(
                lambda t: ocp.RestoreArgs(), template)
        else:
            restore_args = jax.tree_util.tree_map(
                arg, template, shardings,
                is_leaf=lambda x: x is None or hasattr(x, "shape")
                or hasattr(x, "device_set"))
        return ckptr.restore(
            path, args=ocp.args.PyTreeRestore(restore_args=restore_args))


def save_state_dict(engine, path: str) -> None:
    """Checkpoint a ParallelEngine's full training state (params +
    optimizer slots + buffers) in its CURRENT shardings."""
    save_sharded({"params": engine.params,
                  "opt_state": engine.opt_state,
                  "buffers": engine.buffers}, path)


def load_state_dict(engine, path: str) -> None:
    """Restore a checkpoint into a ParallelEngine, RE-SHARDING every leaf
    to the engine's own layout — the engine may sit on a different mesh /
    zero_stage than the writer (reference converter.py capability)."""
    import jax

    shardings = {
        "params": {k: v.sharding for k, v in engine.params.items()},
        "opt_state": jax.tree_util.tree_map(
            lambda a: a.sharding, engine.opt_state),
        "buffers": {k: v.sharding for k, v in engine.buffers.items()},
    }
    state = load_sharded(path, shardings=shardings)
    engine.params = state["params"]
    engine.opt_state = state["opt_state"]
    engine.buffers = state["buffers"]
