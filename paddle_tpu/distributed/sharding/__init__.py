"""ZeRO-style sharded data parallelism.

Analog of the reference's ``paddle.distributed.sharding``
(distributed/sharding/group_sharded.py facade over
GroupShardedOptimizerStage2 / GroupShardedStage2 / GroupShardedStage3,
fleet/meta_parallel/sharding/group_sharded_*.py ~3.6k LoC of manual
parameter slicing, bucketed reduce-scatter hooks and per-layer
allgather/release).

TPU-native: ZeRO is a *sharding declaration*, not a runtime. Over the
"sharding" mesh axis:
  stage 1 — optimizer slots sharded;
  stage 2 — + gradients reduce-scattered (XLA emits ReduceScatter when
            grad consumers are sharded);
  stage 3 — + parameters sharded, all-gathered just-in-time in forward
            (GSPMD inserts the all-gathers where needed).
The ParallelEngine (distributed/spmd.py) realises the declaration; this
module provides the reference-shaped facade.
"""
from __future__ import annotations

from typing import Optional

from .. import env as _env
from ..spmd import ParallelEngine

__all__ = ["group_sharded_parallel", "save_group_sharded_model",
           "GroupShardedStage2", "GroupShardedStage3",
           "GroupShardedOptimizerStage2"]


class _ShardedModelProxy:
    """Returned by group_sharded_parallel: behaves like the model, runs
    train steps through a zero-staged ParallelEngine."""

    def __init__(self, model, optimizer, level, scaler=None,
                 loss_fn=None):
        self._model = model
        self._optimizer = optimizer
        self._level = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
        self._scaler = scaler
        self._engine: Optional[ParallelEngine] = None
        self._loss_fn = loss_fn

    def __getattr__(self, item):
        return getattr(self._model, item)

    def __call__(self, *args, **kwargs):
        return self._model(*args, **kwargs)

    def train_step(self, inputs, labels=(), loss_fn=None):
        if self._engine is None:
            self._engine = ParallelEngine(
                self._model, self._optimizer, loss_fn or self._loss_fn,
                mesh=_env.get_mesh(), zero_stage=self._level)
        return self._engine.train_step(inputs, labels)

    def sync(self):
        if self._engine is not None:
            self._engine.sync_to_model()


def group_sharded_parallel(model, optimizer, level, scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, loss_fn=None):
    """Reference: distributed/sharding/group_sharded.py
    group_sharded_parallel(model, optimizer, level∈{os,os_g,p_g_os}).

    offload/buffer/segment knobs are accepted for parity; XLA manages HBM
    residency (offload maps to jax host-memory spaces in a later round).
    """
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError("level must be one of 'os', 'os_g', 'p_g_os'")
    if _env.get_mesh() is None:
        n = _env.device_count()
        _env.build_mesh({"data": 1, "pipe": 1, "sharding": n, "sep": 1,
                         "expert": 1, "model": 1})
    proxy = _ShardedModelProxy(model, optimizer, level, scaler, loss_fn)
    return proxy, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    from ...framework.io import save
    if isinstance(model, _ShardedModelProxy):
        model.sync()
        model = model._model
    save(model.state_dict(), output + ".pdmodel")
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt")


# API-parity aliases: the stage classes in the reference wrap models/
# optimizers; here the distinction is only the declared level.
class GroupShardedStage2(_ShardedModelProxy):
    def __init__(self, model, optimizer, group=None, sync_buffers=False,
                 buffer_max_size=2 ** 23, auto_refresh_trainable=True,
                 device="tpu"):
        super().__init__(model, optimizer, "os_g")


class GroupShardedStage3(_ShardedModelProxy):
    def __init__(self, model, optimizer, group=None, sync_buffers=False,
                 device="tpu", segment_size=2 ** 20, pertrain_sync_models=True,
                 offload=False, sync_comm=False):
        super().__init__(model, optimizer, "p_g_os")


class GroupShardedOptimizerStage2:
    def __init__(self, params, optim, group=None, offload=False,
                 device="tpu", **kw):
        self._optim = optim

    def __getattr__(self, item):
        return getattr(self._optim, item)
