"""Sequence/context parallelism: ring attention over the "sep" mesh axis.

The reference has NO long-context parallelism (SURVEY.md §5 — grep-verified
absent); this is a new TPU-first capability required of this framework:
sequences sharded over mesh axis "sep", attention computed blockwise while
K/V chunks rotate around the ring via ``lax.ppermute`` (one ICI hop per
step), with an online-softmax accumulator so memory stays O(L/sp) per chip
(Ring Attention; blockwise attention numerics).

``ring_attention`` is shaped like ``scaled_dot_product_attention``
([B, L_local, H, D] in, same out) and is differentiable — reverse-mode AD
transposes the ppermute ring into the reverse rotation.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np

from ..framework.tensor import Tensor
from . import env as _env

__all__ = ["ring_attention", "RingAttention", "split_sequence",
           "gather_sequence"]


def _ring_attention_arrays(q, k, v, axis_name: str, axis_size: int,
                           causal: bool, scale: Optional[float]):
    import jax
    import jax.numpy as jnp

    b, lq, h, d = q.shape
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    my = jax.lax.axis_index(axis_name)

    # operands keep their storage dtype (bf16 -> native MXU rate); the
    # f32 numerics live in the accumulators via preferred_element_type
    qs = q * jnp.asarray(s, q.dtype)
    neg = jnp.asarray(-1e30, jnp.float32)

    def block(qf, kf, vf, q_off, k_off):
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kf,
                            preferred_element_type=jnp.float32)
        if causal:
            qi = q_off + jnp.arange(lq)[:, None]
            ki = k_off + jnp.arange(kf.shape[1])[None, :]
            logits = jnp.where((ki <= qi)[None, None], logits, neg)
        m = logits.max(-1)                                  # [b,h,q]
        p = jnp.exp(logits - m[..., None])
        l = p.sum(-1)                                       # [b,h,q]
        o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(vf.dtype), vf,
                       preferred_element_type=jnp.float32)
        return m, l, o

    # online-softmax accumulation across ring steps
    m_acc = jnp.full((b, h, lq), -jnp.inf, jnp.float32)
    l_acc = jnp.zeros((b, h, lq), jnp.float32)
    o_acc = jnp.zeros((b, lq, h, d), jnp.float32)
    k_cur, v_cur = k, v
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    q_off = my * lq
    for step in range(axis_size):
        src = (my - step) % axis_size  # whose K/V we hold this step
        k_off = src * k.shape[1]
        m_b, l_b, o_b = block(qs, k_cur, v_cur, q_off, k_off)
        m_new = jnp.maximum(m_acc, m_b)
        c_old = jnp.exp(m_acc - m_new)
        c_new = jnp.exp(m_b - m_new)
        l_acc = l_acc * c_old + l_b * c_new
        o_acc = o_acc * c_old.transpose(0, 2, 1)[..., None] + \
            o_b * c_new.transpose(0, 2, 1)[..., None]
        m_acc = m_new
        if step + 1 < axis_size:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)

    out = o_acc / jnp.maximum(
        l_acc.transpose(0, 2, 1), 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                   scale: Optional[float] = None, mesh=None):
    """Blockwise ring attention.

    Call INSIDE a program sharded over ``axis_name`` (e.g. via
    ``sequence_parallel_attention`` below or a shard_map region), with
    q/k/v holding this rank's sequence chunk [B, L/sp, H, D].
    """
    import jax
    mesh = mesh or _env.get_mesh()
    size = mesh.shape[axis_name] if mesh is not None else 1
    raw = (q._data, k._data, v._data) if isinstance(q, Tensor) \
        else (q, k, v)
    if size <= 1:
        from ..ops.registry import get_op
        out = get_op("scaled_dot_product_attention").fn(
            *raw, None, None, is_causal=causal, scale=scale)
        return Tensor(out) if isinstance(q, Tensor) else out
    out = _ring_attention_arrays(*raw, axis_name=axis_name, axis_size=size,
                                 causal=causal, scale=scale)
    return Tensor(out) if isinstance(q, Tensor) else out


class RingAttention:
    """Functional wrapper binding a mesh + axis (API convenience)."""

    def __init__(self, axis_name="sep", causal=True, mesh=None):
        self.axis_name = axis_name
        self.causal = causal
        self.mesh = mesh

    def __call__(self, q, k, v):
        return ring_attention(q, k, v, axis_name=self.axis_name,
                              causal=self.causal, mesh=self.mesh)


def sequence_parallel_attention(q, k, v, mesh=None, causal=False):
    """Whole-sequence entry point: q/k/v [B, L, H, D] get sequence-sharded
    over "sep"; returns full-length output. Run under jit with the mesh."""
    import jax
    from jax.sharding import PartitionSpec as PS

    mesh = mesh or _env.get_mesh()
    size = mesh.shape.get("sep", 1) if mesh is not None else 1
    raw = (q._data, k._data, v._data) if isinstance(q, Tensor) else (q, k, v)
    if size <= 1:
        return ring_attention(q, k, v, mesh=mesh, causal=causal)
    fn = jax.shard_map(
        partial(_ring_attention_arrays, axis_name="sep", axis_size=size,
                causal=causal, scale=None),
        mesh=mesh,
        in_specs=(PS(None, "sep"), PS(None, "sep"), PS(None, "sep")),
        out_specs=PS(None, "sep"),
        axis_names=frozenset({"sep"}), check_vma=False)
    out = fn(*raw)
    return Tensor(out) if isinstance(q, Tensor) else out


def split_sequence(x, mesh=None, axis=1):
    """Shard a [B, L, ...] tensor's sequence dim over "sep"."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as PS
    mesh = mesh or _env.get_mesh()
    spec = [None] * (x.ndim if not isinstance(x, Tensor) else len(x.shape))
    spec[axis] = "sep"
    data = x._data if isinstance(x, Tensor) else x
    out = jax.device_put(data, NamedSharding(mesh, PS(*spec)))
    return Tensor(out) if isinstance(x, Tensor) else out


def gather_sequence(x, mesh=None, axis=1):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as PS
    mesh = mesh or _env.get_mesh()
    data = x._data if isinstance(x, Tensor) else x
    out = jax.device_put(data, NamedSharding(mesh, PS()))
    return Tensor(out) if isinstance(x, Tensor) else out
