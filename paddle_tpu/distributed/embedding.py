"""Mesh-sharded large-embedding ranking.

The TPU-native counterpart of the reference's parameter-server sparse
tables (paddle/fluid/distributed/ps/table/ sharded embeddings,
accessor/ frequency+decay bookkeeping; see README.md "Scope decision"
— the async brpc PS product itself is descoped, THIS is what replaces
its workload on a TPU mesh):

* the table is one dense [vocab, dim] parameter ROW-SHARDED over a mesh
  axis — each device holds vocab/n rows in HBM, so table capacity
  scales linearly with the mesh exactly like adding PS shards;
* lookup is a plain gather: GSPMD partitions it and inserts the ICI
  collectives that play the role of the PS's pull RPCs — synchronous,
  inside the jitted train step, on interconnect that is orders of
  magnitude faster than the PS's commodity ethernet;
* the gradient of a gather is a scatter-add onto the sharded rows —
  the push RPC analog, again compiled to collectives;
* per-row hit counters (the accessor's frequency statistic) ride along
  as a sharded int32 buffer updated in-graph; eviction/compaction is an
  OFFLINE pass over the counters (``hot_rows``/``reset_frequency``),
  not a dynamic-shape table mutation — XLA requires static shapes, and
  CTR practice compacts between training runs anyway.
"""
from __future__ import annotations

import numpy as np

from .. import nn
from ..framework.tensor import Tensor
from ..nn import functional as F
from .fleet.meta_parallel.parallel_layers.mp_layers import (
    constrain, mark_sharding,
)

__all__ = ["ShardedEmbedding"]


class ShardedEmbedding(nn.Layer):
    """Embedding with rows sharded over ``shard_axis`` of the mesh.

    Unlike ``VocabParallelEmbedding`` (mp_layers.py — tensor-parallel
    vocab split inside one transformer), this is the CAPACITY-scaling
    form for ranking workloads: shard over the large axis of the mesh
    ("sharding"/"data"), track row frequencies, and expect vocabularies
    that only fit because they are spread across every device.
    """

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 shard_axis: str = "sharding", sparse: bool = False,
                 padding_idx=None, track_frequency: bool = False,
                 weight_attr=None, name=None):
        super().__init__()
        import jax.numpy as jnp
        self._num = int(num_embeddings)
        self._dim = int(embedding_dim)
        self._padding_idx = padding_idx
        self._track = bool(track_frequency)
        # `sparse=True` in the reference selects sparse gradient rows;
        # here the gather's transpose IS a scatter-add — accepted for
        # API parity, nothing to switch
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        mark_sharding(self.weight, shard_axis, None)
        if self._track:
            counts = Tensor(jnp.zeros([num_embeddings], jnp.int32))
            self.register_buffer("_counts", counts)
            mark_sharding(self._buffers["_counts"], shard_axis)

    def forward(self, ids):
        import jax.numpy as jnp
        out = F.embedding(ids, self.weight,
                          padding_idx=self._padding_idx)
        if self._track and self.training:
            arr = ids._data if isinstance(ids, Tensor) else jnp.asarray(ids)
            batch_counts = jnp.bincount(
                arr.reshape(-1).astype(jnp.int32), length=self._num
            ).astype(jnp.int32)
            if self._padding_idx is not None:
                # padding slots are not real lookups — counting them
                # would make the padding row the "hottest" and corrupt
                # the eviction signal these counters feed
                batch_counts = batch_counts.at[
                    int(self._padding_idx) % self._num].set(0)
            # buffer write: functional_state threads it through jitted
            # steps exactly like BatchNorm running stats
            self._buffers["_counts"]._data = \
                self._counts._data + batch_counts
        # batch stays split over "data" whatever the table's axis is
        nd = len(out.shape)
        return constrain(out, *(("data",) + (None,) * (nd - 1)))

    # -- offline accessor surface (reference accessor/: show/click
    # frequency stats feeding admission & eviction) ----------------------
    def frequency(self) -> np.ndarray:
        if not self._track:
            raise RuntimeError(
                "construct with track_frequency=True to record hits")
        return np.asarray(self._counts.numpy())

    def hot_rows(self, k: int) -> np.ndarray:
        """Ids of the k most-frequently-looked-up rows (descending)."""
        freq = self.frequency()
        k = min(int(k), freq.shape[0])
        top = np.argpartition(-freq, k - 1)[:k]
        return top[np.argsort(-freq[top], kind="stable")]

    def reset_frequency(self) -> None:
        import jax.numpy as jnp
        if self._track:
            self._buffers["_counts"]._data = jnp.zeros(
                [self._num], jnp.int32)
