"""SPMD engine: turns an annotated Layer + Optimizer into ONE sharded,
jit-compiled train step over the hybrid mesh.

This is the TPU-native replacement for the reference's whole per-strategy
executor zoo — dygraph DataParallel's bucketed Reducer
(fluid/imperative/reducer.cc), the sharding meta-optimizers, and the
meta_parallel wrappers: data/tensor/sharding parallelism are expressed as
shardings on the parameters / optimizer slots / batch of a single jitted
function, and XLA inserts + overlaps every collective (grad psum ≙ the
Reducer, slot sharding ≙ ZeRO-1, grad reduce-scatter ≙ ZeRO-2, param
all-gather ≙ ZeRO-3).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

from ..framework import random as _random
from ..framework.tensor import Tensor, no_grad_guard
from ..nn.layer.layers import functional_call, get_buffers_tree, \
    get_params_tree
from . import env as _env

__all__ = ["param_pspec", "param_shardings", "batch_pspec",
           "ParallelEngine"]


def _P(*args):
    from jax.sharding import PartitionSpec
    return PartitionSpec(*args)


def param_pspec(name: str, param, zero_stage=0, mesh=None):
    """PartitionSpec for a parameter: explicit ``mesh_axes`` annotation
    (set by the TP layers) wins; otherwise ZeRO-3 shards the first
    divisible dim over "sharding"; otherwise replicated."""
    axes = getattr(param, "mesh_axes", None)
    if axes is not None:
        return _P(*axes)
    if zero_stage >= 3 and mesh is not None:
        deg = mesh.shape.get("sharding", 1)
        if deg > 1:
            shape = tuple(param.shape) if hasattr(param, "shape") else ()
            for i, s in enumerate(shape):
                if s % deg == 0:
                    return _P(*([None] * i + ["sharding"]))
    return _P()


def param_shardings(layer, mesh, zero_stage=0):
    from jax.sharding import NamedSharding
    out = {}
    for name, p in layer.named_parameters():
        out[name] = NamedSharding(
            mesh, param_pspec(name, p, zero_stage, mesh))
    return out


def slot_pspec(pspec, param_shape, mesh, zero_stage):
    """Optimizer-slot sharding: follow the param; ZeRO>=1 additionally
    shards replicated slots over "sharding"."""
    if zero_stage >= 1 and mesh.shape.get("sharding", 1) > 1 and \
            all(a is None for a in (pspec or ())):
        deg = mesh.shape["sharding"]
        for i, s in enumerate(param_shape):
            if s % deg == 0:
                return _P(*([None] * i + ["sharding"]))
    return pspec


def batch_pspec(mesh):
    """Batch dim sharded over data × sharding (the reference's dp and
    sharding groups both consume distinct batch slices)."""
    axes = [a for a in ("data", "sharding") if mesh.shape.get(a, 1) > 1]
    if not axes:
        return _P()
    return _P(tuple(axes) if len(axes) > 1 else axes[0])


class ParallelEngine:
    """Holds sharded (params, opt_state, buffers) and the compiled step.

    Used by fleet.distributed_model/distributed_optimizer under the hood;
    also directly by __graft_entry__.dryrun_multichip.
    """

    def __init__(self, model, optimizer=None, loss_fn=None, mesh=None,
                 zero_stage=0, recompute=False, donate=True):
        import jax
        from jax.sharding import NamedSharding

        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh or _env.get_mesh()
        if self.mesh is None:
            raise ValueError("no mesh: call fleet.init or env.build_mesh")
        self.zero_stage = zero_stage
        self.recompute = recompute
        self._step_count = 0

        model.train()
        params = get_params_tree(model)
        buffers = get_buffers_tree(model)
        self._pshard = param_shardings(model, self.mesh, zero_stage)
        self.params = {k: jax.device_put(v, self._pshard[k])
                       for k, v in params.items()}
        rep = NamedSharding(self.mesh, _P())
        self.buffers = {k: jax.device_put(v, rep)
                        for k, v in buffers.items()}
        if optimizer is not None:
            state = optimizer.init_state(params)
            self._sshard = {
                k: {s: NamedSharding(
                    self.mesh,
                    slot_pspec(self._pshard[k].spec, np.shape(params[k]),
                               self.mesh, zero_stage))
                    for s in slots}
                for k, slots in state["slots"].items()}
            self.opt_state = {
                "step": jax.device_put(state["step"], rep),
                "slots": {k: {s: jax.device_put(a, self._sshard[k][s])
                              for s, a in slots.items()}
                          for k, slots in state["slots"].items()},
            }
        self._train_step = None
        self._donate = donate

    # ------------------------------------------------------------------
    def _build(self, n_inputs):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding

        # TPU Pallas smoke gate: a kernel that cannot lower on this chip
        # must degrade to the lax path, never crash the compiled step
        # (r2 verdict item 1b)
        from ..ops import pallas_smoke
        pallas_smoke.ensure()

        model, opt, loss_fn = self.model, self.optimizer, self.loss_fn
        mesh = self.mesh
        rep = NamedSharding(mesh, _P())
        bshard = NamedSharding(mesh, batch_pspec(mesh))
        clip = getattr(opt, "_grad_clip", None)

        import contextlib

        def _amp_ctx():
            # models decorated via amp.decorate(level="O2") trace their
            # forward under autocast so fp32 inputs are cast to the AMP
            # dtype at dtype-strict ops (conv/matmul)
            level = getattr(model, "_amp_level", "O0")
            if level in ("O1", "O2"):
                from .. import amp as _amp
                return _amp.auto_cast(
                    level=level,
                    dtype=getattr(model, "_amp_dtype", "bfloat16"))
            return contextlib.nullcontext()

        def step(params, opt_state, buffers, key, lr, *arrays):
            inputs = arrays[:n_inputs]
            labels = arrays[n_inputs:]

            def loss_of(p):
                with _random.rng_guard(key), _amp_ctx():
                    from ..nn.layer.layers import functional_state
                    with functional_state(model, p, buffers) as st:
                        with no_grad_guard():
                            ins = [Tensor(a, stop_gradient=True)
                                   for a in inputs]
                            lbl = [Tensor(a) for a in labels]
                            if loss_fn is not None:
                                out = model(*ins)
                                outs = out if isinstance(out, (list, tuple))\
                                    else [out]
                                loss = loss_fn(*outs, *lbl)
                            else:  # model returns (loss, ...)
                                out = model(*ins, *lbl)
                                loss = out[0] if isinstance(
                                    out, (list, tuple)) else out
                    nb = st["updated_buffers"]
                lv = loss._data
                if lv.ndim > 0:
                    lv = jnp.mean(lv)
                return lv.astype(jnp.float32), nb

            (loss, new_buffers), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            if clip is not None:
                pairs = clip([(params[k], g) for k, g in grads.items()])
                grads = {k: g for (k, (_, g)) in
                         zip(grads.keys(), pairs)}
            new_params, new_opt = opt.apply_gradients(
                params, grads, opt_state, lr)
            return new_params, new_opt, new_buffers, loss

        state_shardings = (self._pshard,
                           {"step": rep, "slots": self._sshard},
                           {k: rep for k in self.buffers})
        self._train_step = jax.jit(
            step,
            in_shardings=state_shardings + (None, None) +
            tuple([bshard]) * self._n_batch,
            out_shardings=state_shardings + (rep,),
            donate_argnums=(0, 1, 2) if self._donate else (),
        )

    # ------------------------------------------------------------------
    @staticmethod
    def _coerce(seq):
        """numpy-fy host data; pass device arrays through untouched (a
        np.asarray on a jax.Array is a device->host sync + re-upload —
        the r2-measured 449 ms/step on ResNet)."""
        import jax
        items = seq if isinstance(seq, (list, tuple)) else [seq]
        out = []
        for a in items:
            if isinstance(a, jax.Array):
                out.append(a)
            elif isinstance(a, Tensor):
                out.append(a._data)
            else:
                out.append(np.asarray(a))
        return out

    def train_step_async(self, inputs, labels=()):
        """One sharded train step; returns the loss as a DEVICE scalar
        without blocking.  jax's async dispatch queues successive steps
        back-to-back on the chip; fetch the loss (float()) only when you
        need the number.  This is the fast path the benchmarks use — the
        blocking form costs a host round-trip per step."""
        import jax
        import jax.numpy as jnp

        ins = self._coerce(inputs)
        lbs = self._coerce(labels)
        if self._train_step is None:
            self._n_batch = len(ins) + len(lbs)
            self._build(len(ins))
        self._step_count += 1
        # derive the per-step dropout key from the user seed (paddle.seed),
        # not a hard-coded constant (r1 verdict weak item 6)
        base = jax.random.key(_random.default_generator().initial_seed())
        key = jax.random.fold_in(base, self._step_count)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        with self.mesh:
            (self.params, self.opt_state, self.buffers,
             loss) = self._train_step(self.params, self.opt_state,
                                      self.buffers, key, lr, *ins, *lbs)
        return loss

    def train_step(self, inputs, labels=()):
        """Run one sharded train step; returns host float loss."""
        return float(self.train_step_async(inputs, labels))

    def device_put_batch(self, inputs, labels=()):
        """Place a host batch on the mesh with the engine's batch sharding
        (transfer once, reuse across steps — e.g. device-resident
        synthetic benches)."""
        import jax
        from jax.sharding import NamedSharding
        bshard = NamedSharding(self.mesh, batch_pspec(self.mesh))
        put = lambda seq: [jax.device_put(a, bshard)
                           for a in self._coerce(seq)]
        return put(inputs), put(labels)

    def sync_to_model(self):
        """Write device state back into the Layer (for save/eval)."""
        import jax
        for name, p in self.model.named_parameters():
            p._data = jax.device_get(self.params[name])
        for name, b in self.model.named_buffers():
            if name in self.buffers:
                b._data = jax.device_get(self.buffers[name])
