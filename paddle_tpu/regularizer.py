"""``paddle.regularizer`` — weight-decay regularizers.

Analog of the reference's python/paddle/regularizer.py (L1Decay/L2Decay).
The classes live in optimizer/optimizer.py because the TPU-native optimizer
applies decay inside the fused jitted update; this module is the canonical
public re-export.
"""
from .optimizer.optimizer import L1Decay, L2Decay  # noqa: F401

__all__ = ["L1Decay", "L2Decay"]
