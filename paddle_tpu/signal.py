"""``paddle.signal`` — STFT / ISTFT.

Reference: python/paddle/signal.py (stft:11x frame+fft composition,
istft overlap-add). TPU-native: framing is a gather-free
strided-reshape + window multiply; the FFT lowers natively in XLA —
the whole transform jits into one fused program.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .framework.tensor import Tensor

__all__ = ["stft", "istft", "frame", "overlap_add"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice overlapping frames (reference signal.py frame)."""
    a = _arr(x)
    if axis not in (-1, a.ndim - 1):
        raise NotImplementedError("frame supports the last axis")
    n = a.shape[-1]
    num = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(frame_length)[None, :]
           + hop_length * jnp.arange(num)[:, None])       # [num, L]
    out = a[..., idx]                                     # [..., num, L]
    # reference layout: [..., frame_length, num_frames]
    return Tensor(jnp.swapaxes(out, -1, -2))


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame (reference signal.py overlap_add): input
    [..., frame_length, num_frames] -> [..., output_len]."""
    a = _arr(x)
    if axis not in (-1, a.ndim - 1):
        raise NotImplementedError("overlap_add supports the last axis")
    frame_length, num = a.shape[-2], a.shape[-1]
    out_len = frame_length + hop_length * (num - 1)
    frames = jnp.swapaxes(a, -1, -2)                      # [..., num, L]
    # one scatter-add over the frame index matrix — an unrolled
    # per-frame loop makes compile time linear in num_frames
    idx = (jnp.arange(frame_length)[None, :]
           + hop_length * jnp.arange(num)[:, None])       # [num, L]
    out = jnp.zeros(a.shape[:-2] + (out_len,), a.dtype)
    out = out.at[..., idx].add(frames)
    return Tensor(out)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False,
         onesided=True, name=None):
    """Short-time Fourier transform (reference signal.py stft).

    x: [B, T] or [T]; returns [B, n_fft//2+1 (or n_fft), num_frames]
    complex.
    """
    a = _arr(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        win = jnp.ones((win_length,), jnp.float32)
    else:
        win = _arr(window).astype(jnp.float32)
    if win_length < n_fft:  # center-pad the window to n_fft (reference)
        lpad = (n_fft - win_length) // 2
        win = jnp.pad(win, (lpad, n_fft - win_length - lpad))
    if center:
        pad = n_fft // 2
        a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(pad, pad)],
                    mode=pad_mode)
    framed = frame(Tensor(a), n_fft, hop_length)._data   # [..., n_fft, F]
    framed = jnp.swapaxes(framed, -1, -2) * win          # [..., F, n_fft]
    spec = jnp.fft.rfft(framed, axis=-1) if onesided else \
        jnp.fft.fft(framed, axis=-1)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
    return Tensor(jnp.swapaxes(spec, -1, -2))            # [..., K, F]


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with window-envelope normalization (reference
    signal.py istft)."""
    spec = _arr(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        win = jnp.ones((win_length,), jnp.float32)
    else:
        win = _arr(window).astype(jnp.float32)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        win = jnp.pad(win, (lpad, n_fft - win_length - lpad))
    frames = jnp.swapaxes(spec, -1, -2)                  # [..., F, K]
    if normalized:
        frames = frames * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
    if onesided:
        if return_complex:
            raise ValueError(
                "return_complex=True requires onesided=False (a onesided "
                "spectrum reconstructs a real signal)")
        wave = jnp.fft.irfft(frames, n=n_fft, axis=-1)
    else:
        wave = jnp.fft.ifft(frames, axis=-1)
        if not return_complex:
            wave = wave.real
    wave = wave * win                                    # [..., F, n_fft]
    out = overlap_add(Tensor(jnp.swapaxes(wave, -1, -2)),
                      hop_length)._data
    # normalize by the summed squared window envelope
    env = overlap_add(
        Tensor(jnp.broadcast_to((win * win)[:, None],
                                (n_fft, frames.shape[-2]))),
        hop_length)._data
    out = out / jnp.maximum(env, 1e-11)
    if center:
        pad = n_fft // 2
        out = out[..., pad:out.shape[-1] - pad]
    if length is not None:
        out = out[..., :length]
    return Tensor(out)
