"""``paddle.sysconfig`` (reference: python/paddle/sysconfig.py) —
include/lib dirs for building C++ extensions against the framework."""
import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include():
    """Directory holding the C headers consumed by cpp_extension builds."""
    return os.path.join(_ROOT, "include")


def get_lib():
    """Directory holding the framework's native shared objects (built on
    demand by utils.cpp_extension)."""
    return os.path.join(_ROOT, "lib")
