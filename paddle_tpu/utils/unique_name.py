"""Reference: python/paddle/utils/unique_name.py (generate/guard/switch)."""
from __future__ import annotations

import contextlib
import threading

__all__ = ["generate", "guard", "switch"]


class _Generator:
    def __init__(self):
        self.ids = {}

    def __call__(self, key):
        self.ids[key] = self.ids.get(key, 0) + 1
        return f"{key}_{self.ids[key] - 1}"


_tls = threading.local()


def _gen() -> _Generator:
    if not hasattr(_tls, "gen"):
        _tls.gen = _Generator()
    return _tls.gen


def generate(key: str) -> str:
    return _gen()(key)


def switch(new_generator=None):
    old = _gen()
    _tls.gen = new_generator or _Generator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        _tls.gen = old
