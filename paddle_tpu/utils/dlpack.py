"""``paddle.utils.dlpack`` (reference: python/paddle/utils/dlpack.py) —
zero-copy tensor exchange with other frameworks via the DLPack protocol,
served by jax's dlpack support."""
from __future__ import annotations

from ..framework.tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor -> DLPack-exporting object (modern protocol: the returned
    object carries ``__dlpack__``/``__dlpack_device__``; any consumer
    framework's ``from_dlpack`` accepts it zero-copy)."""
    import jax
    return x._data if isinstance(x, Tensor) else jax.numpy.asarray(x)


def from_dlpack(ext) -> Tensor:
    """Object exporting ``__dlpack__`` (jax/torch/numpy array or a legacy
    capsule) -> Tensor."""
    import jax
    if hasattr(ext, "__dlpack__"):
        arr = jax.dlpack.from_dlpack(ext)
    else:  # legacy PyCapsule path
        import numpy as _np
        arr = jax.numpy.asarray(_np.from_dlpack(ext)) \
            if hasattr(_np, "from_dlpack") else jax.dlpack.from_dlpack(ext)
    return Tensor(arr, stop_gradient=True)
