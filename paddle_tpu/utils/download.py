"""``paddle.utils.download`` (reference: python/paddle/utils/download.py).

No network egress in this environment: resolution happens against the
local weights cache; a missing file raises with placement instructions
(mirrors the reference's behavior on a failed download, loudly).
"""
from __future__ import annotations

import os
import os.path as osp

__all__ = ["get_weights_path_from_url", "get_path_from_url"]

WEIGHTS_HOME = osp.expanduser(
    os.environ.get("PADDLE_WEIGHTS_HOME", "~/.cache/paddle/hapi/weights"))


def _resolve(url: str, root_dir: str, md5sum=None):
    fname = osp.join(root_dir, url.split("/")[-1])
    if osp.exists(fname):
        if md5sum:
            from ..dataset.common import md5file
            if md5file(fname) != md5sum:
                raise RuntimeError(f"{fname} exists but fails md5 check")
        return fname
    raise RuntimeError(
        f"cannot download {url} (no network egress); place the file at "
        f"{fname}")


def get_weights_path_from_url(url: str, md5sum=None) -> str:
    return _resolve(url, WEIGHTS_HOME, md5sum)


def get_path_from_url(url: str, root_dir: str, md5sum=None,
                      check_exist: bool = True, decompress: bool = True,
                      method: str = "get") -> str:
    path = _resolve(url, root_dir, md5sum)
    for suffix in (".tar.gz", ".tgz", ".zip"):
        if decompress and path.endswith(suffix):
            extracted = path[: -len(suffix)]
            # freshness via a marker file written AFTER extraction
            # (member mtimes are restored from the archive, so comparing
            # the extracted tree's own mtime against the archive is wrong)
            marker = path + ".extracted"
            if check_exist and osp.exists(extracted) and \
                    osp.exists(marker) and \
                    os.path.getmtime(marker) >= os.path.getmtime(path):
                return extracted
            import tarfile
            import zipfile
            dst = osp.dirname(path)
            if suffix == ".zip":
                with zipfile.ZipFile(path) as z:
                    # reject members that would escape the destination
                    # (absolute paths / ".." traversal in a tampered cache)
                    base = osp.realpath(dst)
                    for name in z.namelist():
                        target = osp.realpath(osp.join(dst, name))
                        if not (target == base
                                or target.startswith(base + os.sep)):
                            raise RuntimeError(
                                f"unsafe zip member path: {name!r}")
                    z.extractall(dst)
            else:
                with tarfile.open(path) as t:
                    if hasattr(tarfile, "data_filter"):
                        t.extractall(dst, filter="data")
                    else:  # pre-3.12: no filter= support
                        t.extractall(dst)
            with open(marker, "w") as f:
                f.write("ok")
            return extracted if osp.exists(extracted) else path
    return path
