"""Reference-format ``.pdparams`` checkpoint loading.

The reference ships downloadable ImageNet weights for every vision model
(reference: python/paddle/vision/models/resnet.py:26-62 model_urls,
python/paddle/utils/download.py:1 resolution, python/paddle/framework/io.py:791
load). Its ``.pdparams`` files are pickles of ``{structured_name: ndarray}``
— Tensors are converted to numpy before pickling — plus an optional
``StructuredToParameterName@@`` bookkeeping entry.

This module reads that exact on-disk format so reference checkpoints drop
straight into paddle_tpu models:

* unpickling is RESTRICTED to numpy reconstruction + builtin containers —
  a ``.pdparams`` from an untrusted cache cannot execute code;
* structured names match 1:1 (paddle_tpu layers use the reference naming,
  including BatchNorm's ``_mean``/``_variance`` buffers), so conversion is
  key filtering + dtype alignment, not a rename table;
* conv weights stay OIHW in both frameworks (paddle_tpu's NHWC mode
  transposes activations, never weights — vision/models/resnet.py:7), so
  the same file serves both layouts.

No network egress exists in this environment, so ``pretrained=True``
resolves against the local weights cache (``PADDLE_WEIGHTS_HOME``) and a
model's ``pretrained=`` argument also accepts a direct file path.
"""
from __future__ import annotations

import io
import pickle

import numpy as np

__all__ = ["load_pdparams", "load_pretrained"]

# reference python/paddle/fluid/framework.py: extra key carried in saved
# state dicts mapping structured names -> parameter names
_STRUCT_KEY = "StructuredToParameterName@@"

_ALLOWED = {
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "_reconstruct"),  # numpy 2.x module path
    ("numpy._core.multiarray", "scalar"),
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy.core.numeric", "_frombuffer"),
    ("numpy._core.numeric", "_frombuffer"),
    ("collections", "OrderedDict"),
    # protocol<=2 numpy array payloads are latin-1 strings decoded via this
    ("_codecs", "encode"),
}


class _RestrictedUnpickler(pickle.Unpickler):
    """Only numpy array reconstruction and builtin containers may load.

    A ``.pdparams`` is a pickle; pickles execute arbitrary callables on
    load. Reference files only ever contain numpy arrays in dicts, so
    everything else is rejected loudly (defense for a tampered local
    weights cache)."""

    def find_class(self, module, name):
        if (module, name) in _ALLOWED:
            return super().find_class(module, name)
        # numpy scalar types (float32, int64, ...) used by dtype pickling;
        # scalar TYPES only — np.save/np.load/etc. are callables an
        # attacker could smuggle in via REDUCE
        if module in ("numpy", "numpy.core.multiarray",
                      "numpy._core.multiarray") and hasattr(np, name):
            obj = getattr(np, name)
            if isinstance(obj, type) and issubclass(obj, np.generic):
                return obj
        raise pickle.UnpicklingError(
            f"refusing to unpickle {module}.{name}: .pdparams files may "
            f"only contain numpy arrays")


def load_pdparams(path: str) -> dict:
    """Load a reference-format ``.pdparams`` into ``{name: np.ndarray}``.

    Drops the ``StructuredToParameterName@@`` bookkeeping entry and
    flattens one level of nesting (optimizer checkpoints store master
    weights in a sub-dict)."""
    with open(path, "rb") as f:
        raw = _RestrictedUnpickler(f).load()
    if not isinstance(raw, dict):
        raise ValueError(
            f"{path}: expected a pickled state dict, got {type(raw)}")
    out = {}
    for k, v in raw.items():
        if k == _STRUCT_KEY:
            continue
        if isinstance(v, np.ndarray):
            out[str(k)] = v
        elif isinstance(v, dict):
            if v.get("__bf16__") and isinstance(v.get("data"),
                                                np.ndarray):
                # this framework's own save() tags bfloat16 arrays as a
                # uint16 view (framework/io.py) — decode under the
                # ORIGINAL key, not a mangled "name.data"
                import ml_dtypes
                out[str(k)] = v["data"].view(ml_dtypes.bfloat16)
                continue
            for kk, vv in v.items():
                if isinstance(vv, np.ndarray):
                    out[f"{k}.{kk}"] = vv
        elif np.isscalar(v):
            out[str(k)] = np.asarray(v)
    return out


def convert_state_dict(raw: dict, model) -> dict:
    """Align a raw ``{name: ndarray}`` dict to ``model``'s state_dict:
    keep matching keys, cast dtypes to the model's, verify shapes.
    Returns the Tensor-valued dict ready for ``set_state_dict``."""
    from ..framework.tensor import Tensor
    import jax.numpy as jnp

    target = model.state_dict()
    missing = [k for k in target if k not in raw]
    if missing:
        raise ValueError(
            f"checkpoint is missing {len(missing)} keys, e.g. "
            f"{missing[:5]} — architecture mismatch?")
    def _squeezed(shape):
        return tuple(d for d in shape if d != 1)

    out = {}
    for k, t in target.items():
        arr = raw[k]
        if tuple(arr.shape) != tuple(t.shape):
            # only rank-1 padding differences ((N,) vs (N,1)) may reshape;
            # an arbitrary same-size reshape would silently load a
            # transposed matrix as garbage
            if _squeezed(arr.shape) != _squeezed(t.shape):
                raise ValueError(
                    f"shape mismatch for {k}: checkpoint {arr.shape} vs "
                    f"model {tuple(t.shape)}")
            arr = arr.reshape(tuple(t.shape))
        out[k] = Tensor(jnp.asarray(arr, dtype=t._data.dtype),
                        stop_gradient=True)
    return out


def load_pretrained(model, arch: str, model_urls: dict, pretrained):
    """Shared ``pretrained=`` implementation for the model zoo.

    ``pretrained`` may be a direct ``.pdparams`` path (offline-friendly) or
    ``True``, which resolves ``model_urls[arch]`` against the local weights
    cache exactly like the reference's ``get_weights_path_from_url``
    (reference resnet.py:317-323)."""
    if isinstance(pretrained, str):
        path = pretrained
    else:
        if arch not in model_urls:
            raise ValueError(
                f"{arch} has no pretrained weights; set pretrained=False "
                f"or pass a .pdparams path")
        from .download import get_weights_path_from_url
        url, md5 = model_urls[arch]
        path = get_weights_path_from_url(url, md5)
    state = convert_state_dict(load_pdparams(path), model)
    model.set_state_dict(state)
    return model
