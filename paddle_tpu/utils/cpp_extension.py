"""Custom C++ op toolchain (``paddle.utils.cpp_extension``).

Reference: python/paddle/utils/cpp_extension/cpp_extension.py —
``load()`` JIT-compiles a C++/CUDA source registering ops via
``PD_BUILD_OP`` (framework/custom_operator.cc) and returns a module of
generated Python wrappers; ``setup()`` is the setuptools variant.

TPU-native: device compute belongs to XLA/Pallas, so a "custom op" here
is host-side C++ with a C ABI (data prep, tokenizers, samplers, IO —
the roles the reference's CPU custom ops actually play), compiled with
the system toolchain and bound through ctypes. The returned module
exposes one Python callable per exported ``extern "C"`` symbol; a
signature table maps numpy arrays to pointers. Ops that should join the
autograd tape can be registered with ``register_as_op`` (pure_callback
under jit).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import re
import subprocess
import types
from typing import Dict, Optional, Sequence

import numpy as np

__all__ = ["load", "get_build_directory", "CppExtension",
           "CUDAExtension", "setup"]

_CACHE_ENV = "PADDLE_EXTENSION_DIR"


def get_build_directory() -> str:
    d = os.environ.get(_CACHE_ENV) or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu_extensions")
    os.makedirs(d, exist_ok=True)
    return d


class CppExtension:
    """setup()-style description (reference CppExtension); carried for
    API parity — building happens through load()."""

    def __init__(self, sources: Sequence[str], *args, **kwargs):
        self.sources = list(sources)
        self.extra_compile_args = kwargs.get("extra_compile_args", [])


_C_TYPES = {
    "void": None,
    "int": ctypes.c_int,
    "long": ctypes.c_long,
    "long long": ctypes.c_longlong,
    "float": ctypes.c_float,
    "double": ctypes.c_double,
    "int*": ctypes.POINTER(ctypes.c_int),
    "long*": ctypes.POINTER(ctypes.c_long),
    "float*": ctypes.POINTER(ctypes.c_float),
    "double*": ctypes.POINTER(ctypes.c_double),
    "const int*": ctypes.POINTER(ctypes.c_int),
    "const long*": ctypes.POINTER(ctypes.c_long),
    "const float*": ctypes.POINTER(ctypes.c_float),
    "const double*": ctypes.POINTER(ctypes.c_double),
    "const char*": ctypes.c_char_p,
    "char*": ctypes.c_char_p,
}

# type token: "long long" before "long" so backtracking can't misbind a
# two-word type's first word as the whole return type
_TYPE_TOKEN = (r"(?:const\s+)?(?:unsigned\s+)?"
               r"(?:long\s+long|[A-Za-z_]\w*)\s*\*?")


def _parse_signatures(source: str) -> Dict[str, Optional[tuple]]:
    """Best-effort parse of `extern "C"` function signatures so ctypes
    bindings get argtypes/restype. Functions with unrecognized types map
    to None and are exported UNTYPED (ctypes defaults)."""
    sigs: Dict[str, Optional[tuple]] = {}
    pat = re.compile(
        r'(?:extern\s+"C"\s+)?'
        r'(?P<ret>' + _TYPE_TOKEN + r')\s+'
        r'(?P<name>\w+)\s*\((?P<args>[^)]*)\)\s*\{')

    def norm(t):
        # canonical form: single spaces, '*' glued to the type name
        t = re.sub(r"\s+", " ", t).strip()
        return t.replace(" *", "*")

    for m in pat.finditer(source):
        name = m.group("name")
        ret = norm(m.group("ret"))
        args = []
        ok = ret in _C_TYPES
        for a in m.group("args").split(","):
            a = a.strip()
            if not a or a == "void":
                continue
            # drop the parameter name
            a = norm(re.sub(r"\s*\w+$", "", a))
            if a not in _C_TYPES or _C_TYPES[a] is None:
                ok = False
                break
            args.append(_C_TYPES[a])
        sigs[name] = (_C_TYPES[ret], args) if ok else None
    return sigs


def _as_ctypes_arg(a, expected):
    if isinstance(a, np.ndarray):
        if expected is not None:
            return a.ctypes.data_as(expected)
        # untyped function: pass a c_void_p, NOT the bare int address —
        # ctypes masks bare ints to C int width, truncating the pointer
        return a.ctypes.data_as(ctypes.c_void_p)
    if isinstance(a, str):
        return a.encode()
    return a


def load(name: str, sources: Sequence[str],
         extra_cxx_cflags: Optional[Sequence[str]] = None,
         extra_ldflags: Optional[Sequence[str]] = None,
         build_directory: Optional[str] = None,
         verbose: bool = False, **kwargs) -> types.SimpleNamespace:
    """JIT-build a C++ extension and return a module-like namespace of
    its ``extern "C"`` functions (reference cpp_extension.py:738 load).
    Recompiles only when sources change (content hash in the .so name).
    """
    build_dir = build_directory or get_build_directory()
    srcs = [os.path.abspath(s) for s in sources]
    for s in srcs:
        if not os.path.exists(s):
            raise FileNotFoundError(s)
    content = "".join(open(s).read() for s in srcs)
    tag = hashlib.sha256(
        (content + repr(extra_cxx_cflags) + repr(extra_ldflags))
        .encode()).hexdigest()[:16]
    so_path = os.path.join(build_dir, f"{name}_{tag}.so")
    if not os.path.exists(so_path):
        # compile to a private temp path and rename atomically: a killed
        # or concurrent build must never leave a truncated .so that
        # poisons the content-hash cache forever
        tmp_path = f"{so_path}.tmp.{os.getpid()}"
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               *(extra_cxx_cflags or []), "-o", tmp_path, *srcs,
               *(extra_ldflags or [])]
        if verbose:
            print("[cpp_extension]", " ".join(cmd))
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=600)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"extension '{name}' failed to build:\n{proc.stderr}")
            os.rename(tmp_path, so_path)
        finally:
            if os.path.exists(tmp_path):
                os.unlink(tmp_path)
    lib = ctypes.CDLL(so_path)
    sigs = _parse_signatures(content)

    ns = types.SimpleNamespace(__name__=name, __so_path__=so_path,
                               __lib__=lib)
    for fname, sig in sigs.items():
        fn = getattr(lib, fname, None)
        if fn is None:
            continue
        argtypes = None
        if sig is not None:
            ret, argtypes = sig
            fn.restype = ret
            fn.argtypes = argtypes

        def make(fn=fn, argtypes=argtypes, fname=fname):
            def call(*args):
                conv = [_as_ctypes_arg(a, t)
                        for a, t in zip(args, argtypes)] if argtypes \
                    else [_as_ctypes_arg(a, None) for a in args]
                return fn(*conv)
            call.__name__ = fname
            return call

        setattr(ns, fname, make())
    return ns


class CUDAExtension(CppExtension):
    """Accepted for porting convenience: on this backend there is no
    nvcc — the sources build as host C++ (device compute belongs to
    XLA/Pallas). Construction warns so the port is a conscious one."""

    def __init__(self, sources, *args, **kwargs):
        import warnings
        warnings.warn(
            "CUDAExtension: no CUDA toolchain on the TPU backend; "
            "building sources as host C++ (.cu files are rejected). "
            "Port device kernels to Pallas (ops/pallas_kernels.py "
            "pattern) instead", UserWarning, stacklevel=2)
        bad = [s for s in sources if str(s).endswith((".cu", ".cuh"))]
        if bad:
            raise ValueError(f"cannot compile CUDA sources here: {bad}")
        super().__init__(sources, *args, **kwargs)


def setup(name=None, ext_modules=None, **kwargs):
    """Reference cpp_extension.setup: build the extension(s) at install
    time. Here it eagerly JIT-builds each extension through load() and
    returns the namespaces (no setuptools involvement — the .so cache
    under get_build_directory() is the 'install')."""
    exts = ext_modules if isinstance(ext_modules, (list, tuple)) else \
        [ext_modules] if ext_modules else []
    built = []
    for i, ext in enumerate(exts):
        srcs = getattr(ext, "sources", ext)
        built.append(load(f"{name or 'ext'}_{i}", list(srcs)))
    return built[0] if len(built) == 1 else built
