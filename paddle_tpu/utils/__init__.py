"""``paddle.utils`` — misc utilities.

Reference: python/paddle/utils/ (unique_name.py, deprecated.py,
download.py, cpp_extension/). The cpp_extension toolchain is covered by
the native-component build in ``paddle_tpu.lib`` (ctypes/cc — no pybind
in this environment); download is out of scope for an offline image.
"""
from __future__ import annotations

import functools
import warnings

from ..framework import monitor  # noqa: F401  (STAT counters + histograms)
from .. import profiler  # noqa: F401  (span profiler: record/profile/export)
from . import unique_name  # noqa: F401

__all__ = ["unique_name", "deprecated", "try_import", "monitor",
           "profiler", "dlpack", "download", "require_version", "run_check"]
from . import dlpack  # noqa: E402,F401
from . import download  # noqa: E402,F401


def deprecated(update_to: str = "", since: str = "", reason: str = ""):
    """Reference: utils/deprecated.py — warn once per call site."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return deco


def try_import(module_name: str, err_msg: str = None):
    """Reference: utils/lazy_import.py try_import."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed"
        ) from e


def require_version(min_version, max_version=None):
    """Reference utils.require_version: raise unless this framework's
    version is within [min_version, max_version]."""
    from ..version import full_version

    def parse(v):
        return tuple(int(p) for p in str(v).split(".")[:3] if p.isdigit())

    cur = parse(full_version)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {full_version} < required {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {full_version} > allowed {max_version}")


def run_check():
    """Reference utils.run_check: verify the install can compute on the
    available device(s); prints a summary like the reference."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    devs = jax.devices()
    x = jnp.ones((128, 128), jnp.float32)
    out = np.asarray(x @ x)
    assert float(out[0, 0]) == 128.0
    print(f"PaddlePaddle (paddle_tpu) works on {len(devs)} "
          f"{devs[0].platform} device(s) [{devs[0].device_kind}].")
    print("PaddlePaddle (paddle_tpu) is installed successfully!")
