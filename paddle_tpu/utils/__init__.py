"""``paddle.utils`` — misc utilities.

Reference: python/paddle/utils/ (unique_name.py, deprecated.py,
download.py, cpp_extension/). The cpp_extension toolchain is covered by
the native-component build in ``paddle_tpu.lib`` (ctypes/cc — no pybind
in this environment); download is out of scope for an offline image.
"""
from __future__ import annotations

import functools
import warnings

from ..framework import monitor  # noqa: F401  (STAT counters)
from . import unique_name  # noqa: F401

__all__ = ["unique_name", "deprecated", "try_import", "monitor",
           "dlpack", "download"]
from . import dlpack  # noqa: E402,F401
from . import download  # noqa: E402,F401


def deprecated(update_to: str = "", since: str = "", reason: str = ""):
    """Reference: utils/deprecated.py — warn once per call site."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return deco


def try_import(module_name: str, err_msg: str = None):
    """Reference: utils/lazy_import.py try_import."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed"
        ) from e
