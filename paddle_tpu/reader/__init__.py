"""``paddle.reader`` — reader decorators (reference:
python/paddle/reader/decorator.py): composable generators feeding
``paddle.batch`` / DataLoader."""
from __future__ import annotations

import itertools
import random

__all__ = ["cache", "map_readers", "buffered", "compose", "chain",
           "shuffle", "firstn", "xmap_readers", "multiprocess_reader"]


def cache(reader):
    all_data = tuple(reader())

    def cached():
        yield from all_data

    return cached


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    def shuffled():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            random.shuffle(buf)
            yield from buf

    return shuffled


def chain(*readers):
    def chained():
        yield from itertools.chain(*[r() for r in readers])

    return chained


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """check_alignment=True (default) raises ComposeNotAligned when readers
    have different lengths (reference semantics); False truncates to the
    shortest silently."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def composed():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())
        else:
            _sentinel = object()
            for outputs in itertools.zip_longest(*rs, fillvalue=_sentinel):
                if any(o is _sentinel for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(o) for o in outputs), ())

    return composed


def buffered(reader, size):
    """Read ahead into a bounded queue on a background thread. Reader
    exceptions are re-raised in the consumer, never swallowed."""
    import queue
    import threading

    end = object()

    def buffered_reader():
        q = queue.Queue(maxsize=size)

        def fill():
            try:
                for d in reader():
                    q.put(d)
                q.put(end)
            except BaseException as exc:  # noqa: BLE001 — relayed, not hidden
                q.put(exc)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is end:
                break
            if isinstance(e, BaseException):
                raise e
            yield e

    return buffered_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i >= n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads (the reference uses
    threads here too — the heavy multiprocess path is io.DataLoader)."""
    import queue
    import threading

    end = object()

    def xreader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)

        def feed():
            try:
                for i, d in enumerate(reader()):
                    in_q.put((i, d))
                for _ in range(process_num):
                    in_q.put(end)
            except BaseException as exc:  # noqa: BLE001 — relayed below
                out_q.put(exc)

        results = {}

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is end:
                        out_q.put(end)
                        return
                    i, d = item
                    out_q.put((i, mapper(d)))
            except BaseException as exc:  # noqa: BLE001 — relayed below
                out_q.put(exc)

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()
        finished = 0
        next_i = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if isinstance(item, BaseException):
                raise item
            if not order:
                yield item[1]
                continue
            results[item[0]] = item[1]
            while next_i in results:
                yield results.pop(next_i)
                next_i += 1
        if order:
            while next_i in results:
                yield results.pop(next_i)
                next_i += 1

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Kept API-compatible; delegates to chained threads (true multiprocess
    ingestion lives in io.DataLoader over the native shm ring)."""
    return chain(*readers)
