"""``paddle.nn.utils`` (reference: python/paddle/nn/utils/ —
weight_norm_hook.py, spectral_norm_hook.py, transform_parameters.py).

TPU note: weight norm is a reparameterization ``w = g * v / ||v||``
recomputed every forward; expressed in jnp it fuses into the consuming
matmul under jit, so there is no runtime cost to keeping it exact.
"""
from __future__ import annotations

import numpy as np

from ...framework.tensor import Tensor

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters"]


def _norm_except(v, dim):
    import jax.numpy as jnp
    if dim is None:
        return jnp.sqrt(jnp.sum(v * v))
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(v * v, axis=axes, keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize ``layer.<name>`` as g * v/||v|| (reference
    weight_norm_hook.py): registers <name>_g and <name>_v parameters and
    a pre-forward hook that rebuilds the weight each call."""
    import jax.numpy as jnp
    w = getattr(layer, name)
    if not isinstance(w, Tensor):
        raise ValueError(f"layer has no parameter {name!r}")
    g0 = _norm_except(w._data, dim)
    from ...framework.tensor import Parameter
    v = Parameter(jnp.asarray(w._data), name=f"{w.name}_v")
    g = Parameter(jnp.asarray(g0), name=f"{w.name}_g")
    # replace the original parameter; v/g are what the optimizer sees
    del layer._parameters[name]
    layer._parameters[f"{name}_v"] = v
    layer._parameters[f"{name}_g"] = g

    def hook(lyr, inputs):
        vv, gg = lyr._parameters[f"{name}_v"], \
            lyr._parameters[f"{name}_g"]
        # thread the tape so grads reach v and g in eager mode
        from ...autograd import differentiable_apply
        built = differentiable_apply(
            lambda a, b: b * a / jnp.maximum(_norm_except(a, dim), 1e-12),
            vv, gg)
        object.__setattr__(lyr, name, built)
        return None

    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_handle = (handle, name, dim)
    hook(layer, ())          # materialize once so .weight exists now
    return layer


def remove_weight_norm(layer, name="weight"):
    """Bake the current normalized weight back into a plain parameter."""
    import jax.numpy as jnp
    handle, nm, dim = getattr(layer, "_weight_norm_handle",
                              (None, name, 0))
    if handle is None:
        raise ValueError("layer has no weight norm applied")
    handle.remove()
    from ...framework.tensor import Parameter
    v = layer._parameters.pop(f"{nm}_v")
    g = layer._parameters.pop(f"{nm}_g")
    norm = _norm_except(v._data, dim)
    w = Parameter(g._data * v._data / jnp.maximum(norm, 1e-12))
    layer._parameters[nm] = w
    object.__setattr__(layer, nm, w)
    del layer._weight_norm_handle
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Spectral normalization hook (reference spectral_norm_hook.py):
    divides the weight by its leading singular value, estimated by
    power iteration refreshed each forward."""
    import jax.numpy as jnp
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    mat = np.asarray(w._data)
    h = mat.shape[dim]
    rest = int(np.prod(mat.shape)) // h
    rng = np.random.RandomState(0)
    layer._sn_u = jnp.asarray(rng.randn(h).astype(np.float32))
    layer._sn_state = (name, dim, int(n_power_iterations), float(eps))

    def hook(lyr, inputs):
        import jax
        nm, d, iters, e = lyr._sn_state
        ww = lyr._parameters[nm + "_orig"]
        m = jnp.moveaxis(ww._data, d, 0).reshape(h, rest)
        u = lyr._sn_u
        # v is always derived once from the stored u so iters=0 (reuse
        # the converged estimate, reference-legal) still defines sigma
        v = m.T @ u
        v = v / jnp.maximum(jnp.linalg.norm(v), e)
        for _ in range(iters):
            u = m @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), e)
            v = m.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), e)
        if not isinstance(u, jax.core.Tracer):
            lyr._sn_u = u       # persist only concrete estimates
        sigma = u @ m @ v
        from ...autograd import differentiable_apply
        built = differentiable_apply(
            lambda a: a / jnp.maximum(sigma, e), ww)
        object.__setattr__(lyr, nm, built)
        return None

    from ...framework.tensor import Parameter
    orig = Parameter(jnp.asarray(w._data), name=f"{w.name}_orig")
    del layer._parameters[name]
    layer._parameters[name + "_orig"] = orig
    layer.register_forward_pre_hook(hook)
    # converge the power iteration once at apply time (the reference
    # refines 1 step/forward; starting converged avoids an early phase
    # where sigma is underestimated and the "normalized" weight isn't)
    layer._sn_state = (name, dim, max(10, int(n_power_iterations)),
                       float(eps))
    hook(layer, ())
    layer._sn_state = (name, dim, int(n_power_iterations), float(eps))
    return layer


def parameters_to_vector(parameters, name=None) -> Tensor:
    """Flatten+concat parameters (reference transform_parameters.py)."""
    import jax.numpy as jnp
    return Tensor(jnp.concatenate(
        [p._data.reshape(-1) for p in parameters]))


def vector_to_parameters(vec, parameters, name=None) -> None:
    """Write a flat vector back into the parameter list, in order."""
    import jax.numpy as jnp
    arr = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    parameters = list(parameters)
    total = sum(int(np.prod(p.shape)) for p in parameters)
    if total != arr.shape[0]:
        raise ValueError(
            f"vector has {arr.shape[0]} elements but parameters hold "
            f"{total}")
    offset = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        p._data = arr[offset:offset + n].reshape(tuple(p.shape)).astype(
            p._data.dtype)
        offset += n
