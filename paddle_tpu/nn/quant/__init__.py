"""``paddle.nn.quant`` import-path parity (reference
python/paddle/nn/quant/ — empty __all__, the quantized layer classes
live here for the slim tooling). The layers themselves are implemented
in paddle_tpu.quantization; this module re-exports them under the
reference path.
"""
from ...quantization import (  # noqa: F401
    FakeQuantAbsMax, MovingAverageAbsMaxScale, QuantizedConv2D,
    QuantizedLinear,
)

__all__ = []
