"""Recurrent layers: SimpleRNN / LSTM / GRU cells and multi-layer,
bidirectional sequence wrappers.

Reference: python/paddle/nn/layer/rnn.py (SimpleRNNCell:270, LSTMCell:406,
GRUCell:563, RNN:714, BiRNN:789, RNNBase → SimpleRNN:1110 / LSTM:1221 /
GRU:1336). Gate semantics match the reference exactly: LSTM gate chunks
are [i, f, g, o] with h = o * tanh(c); GRU splits [r, z, c] with
candidate tanh(x_c + r*h_c) and h = (prev - c) * z + c.

TPU-native: the time loop is a ``lax.scan`` (one compiled step reused
across T — no trace unrolling, MXU-batched gate matmuls), run through
``autograd.differentiable_apply`` so eager ``loss.backward()`` records one
tape node per RNN call while jitted steps trace straight through. The
reference's cuDNN fast path (rnn_op) collapses into XLA's scan fusion.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np

from ...framework.tensor import Tensor
from .layers import Layer

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]


def _uniform_init(rng_shape, hidden_size):
    from ..initializer import Uniform
    k = 1.0 / math.sqrt(hidden_size)
    return Uniform(-k, k)


class RNNCellBase(Layer):
    """Reference rnn.py RNNCellBase: single-step cell with
    ``get_initial_states``."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0):
        import jax.numpy as jnp
        batch = batch_ref.shape[0]
        state_shape = self.state_shape
        if isinstance(state_shape, tuple):
            return tuple(
                Tensor(jnp.full((batch,) + tuple(s), init_value,
                                jnp.float32)) for s in state_shape)
        return Tensor(jnp.full((batch,) + tuple(state_shape), init_value,
                               jnp.float32))


class SimpleRNNCell(RNNCellBase):
    """h' = act(W_ih x + b_ih + W_hh h + b_hh) (reference rnn.py:270)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        init = _uniform_init(None, hidden_size)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], default_initializer=init)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], default_initializer=init)
        self.bias_ih = self.create_parameter(
            [hidden_size], default_initializer=init)
        self.bias_hh = self.create_parameter(
            [hidden_size], default_initializer=init)
        if activation not in ("tanh", "relu"):
            raise ValueError("activation must be tanh or relu")
        self.activation = activation
        self.input_size = input_size
        self.hidden_size = hidden_size

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def _step(self, x, h, w_ih, w_hh, b_ih, b_hh):
        import jax
        import jax.numpy as jnp
        gates = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        nh = act(gates)
        return nh, nh

    def _params(self):
        return [self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh]

    def forward(self, inputs, states=None):
        from ... import autograd
        if states is None:
            states = self.get_initial_states(inputs)
        out, nh = autograd.differentiable_apply(
            lambda x, h, *w: self._step(x, h, *w),
            inputs, states, *self._params())
        return out, nh


class LSTMCell(RNNCellBase):
    """Reference rnn.py:406 — gates chunked [i, f, g, o]."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        init = _uniform_init(None, hidden_size)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], default_initializer=init)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], default_initializer=init)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], default_initializer=init)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], default_initializer=init)
        self.input_size = input_size
        self.hidden_size = hidden_size

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def _step(self, x, h, c, w_ih, w_hh, b_ih, b_hh):
        import jax
        import jax.numpy as jnp
        gates = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        o = jax.nn.sigmoid(o)
        nc = f * c + i * jnp.tanh(g)
        nh = o * jnp.tanh(nc)
        return nh, nc

    def _params(self):
        return [self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh]

    def forward(self, inputs, states=None):
        from ... import autograd
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states
        nh, nc = autograd.differentiable_apply(
            lambda x, hh, cc, *w: self._step(x, hh, cc, *w),
            inputs, h, c, *self._params())
        return nh, (nh, nc)


class GRUCell(RNNCellBase):
    """Reference rnn.py:563 — splits [r, z, c], h = (prev - c) * z + c."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        init = _uniform_init(None, hidden_size)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], default_initializer=init)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], default_initializer=init)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], default_initializer=init)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], default_initializer=init)
        self.input_size = input_size
        self.hidden_size = hidden_size

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def _step(self, x, h, w_ih, w_hh, b_ih, b_hh):
        import jax
        import jax.numpy as jnp
        x_gates = x @ w_ih.T + b_ih
        h_gates = h @ w_hh.T + b_hh
        x_r, x_z, x_c = jnp.split(x_gates, 3, axis=-1)
        h_r, h_z, h_c = jnp.split(h_gates, 3, axis=-1)
        r = jax.nn.sigmoid(x_r + h_r)
        z = jax.nn.sigmoid(x_z + h_z)
        c = jnp.tanh(x_c + r * h_c)
        nh = (h - c) * z + c
        return nh, nh

    def _params(self):
        return [self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh]

    def forward(self, inputs, states=None):
        from ... import autograd
        if states is None:
            states = self.get_initial_states(inputs)
        out, nh = autograd.differentiable_apply(
            lambda x, h, *w: self._step(x, h, *w),
            inputs, states, *self._params())
        return out, nh


def _scan_cell(cell, x_seq, init_states, param_arrays, reverse=False,
               mask=None):
    """lax.scan a cell's _step over time. x_seq: [T, B, I] arrays.

    mask: optional [T, B, 1] bool — variable-length semantics: outputs
    at masked steps are ZERO and the state copies through unchanged, so
    the final state is each example's state at its last valid step.
    State copy-through matches fluid/layers/rnn.py _rnn_dynamic_graph
    (_maybe_copy); zeroed padded outputs follow the rnn OP / the
    tests' rnn_numpy.py oracle (np.where(m_t, y, 0.)) — the fluid
    wrapper itself leaves padded outputs as raw cell outputs, which is
    garbage either way. With reverse=True, lax.scan consumes xs (and
    the aligned mask) back to front — the reference's
    flip(inputs)+flip(mask) formulation."""
    import jax
    import jax.numpy as jnp

    is_lstm = isinstance(cell, LSTMCell)

    def tick(carry, xt):
        if mask is not None:
            xt, mt = xt
        if is_lstm:
            h, c = carry
            nh, nc = cell._step(xt, h, c, *param_arrays)
            if mask is not None:
                return ((jnp.where(mt, nh, h), jnp.where(mt, nc, c)),
                        jnp.where(mt, nh, 0))
            return (nh, nc), nh
        nh, _ = cell._step(xt, carry, *param_arrays)
        if mask is not None:
            return jnp.where(mt, nh, carry), jnp.where(mt, nh, 0)
        return nh, nh

    xs = x_seq if mask is None else (x_seq, mask)
    carry, ys = jax.lax.scan(tick, init_states, xs, reverse=reverse)
    return ys, carry


class RNN(Layer):
    """Runs a cell over a sequence (reference rnn.py:714).

    inputs: [B, T, I] (or [T, B, I] when time_major). Returns
    (outputs, final_states).
    """

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ... import autograd
        import jax.numpy as jnp

        cell = self.cell
        if initial_states is None:
            batch = inputs.shape[0] if not self.time_major else \
                inputs.shape[1]
            zeros = Tensor(jnp.zeros((batch, cell.hidden_size),
                                     jnp.float32))
            initial_states = (zeros, Tensor(zeros._data)) \
                if isinstance(cell, LSTMCell) else zeros

        is_lstm = isinstance(cell, LSTMCell)
        state_tensors = list(initial_states) if is_lstm else \
            [initial_states]
        params = cell._params()
        n_state = len(state_tensors)
        time_major = self.time_major
        reverse = self.is_reverse
        has_len = sequence_length is not None
        if has_len:
            sl = sequence_length._data if isinstance(
                sequence_length, Tensor) else jnp.asarray(
                    np.asarray(sequence_length))
            len_tensors = [Tensor(sl.astype(jnp.int32),
                                  stop_gradient=True)]
        else:
            len_tensors = []

        def fn(x, *rest):
            states = rest[:n_state]
            ws = rest[n_state:n_state + len(params)]
            x_seq = x if time_major else jnp.swapaxes(x, 0, 1)
            mask = None
            if has_len:
                slen = rest[-1]
                T = x_seq.shape[0]
                mask = (jnp.arange(T)[:, None] <
                        slen[None, :])[:, :, None]   # [T, B, 1]
            init = tuple(states) if is_lstm else states[0]
            ys, carry = _scan_cell(cell, x_seq, init, list(ws),
                                   reverse=reverse, mask=mask)
            out = ys if time_major else jnp.swapaxes(ys, 0, 1)
            final = carry if is_lstm else (carry,)
            return (out, *final)

        res = autograd.differentiable_apply(
            fn, inputs, *state_tensors, *params, *len_tensors)
        out = res[0]
        final = tuple(res[1:])
        return out, (final if is_lstm else final[0])


class BiRNN(Layer):
    """Forward + backward cells, outputs concatenated (reference
    rnn.py:789)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...framework.dispatch import call_op
        states_fw, states_bw = (initial_states if initial_states
                                is not None else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, states_fw, sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw, sequence_length)
        out = call_op("concat", [out_fw, out_bw], axis=-1)
        return out, (st_fw, st_bw)


class RNNBase(Layer):
    """Multi-layer, optionally bidirectional stack (reference RNNBase)."""

    _cell_cls = None

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **cell_kwargs):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError(f"bad direction {direction!r}")
        self.bidirectional = direction in ("bidirect", "bidirectional")
        self.num_layers = num_layers
        self.hidden_size = hidden_size
        self.input_size = input_size
        self.time_major = time_major
        self.dropout = dropout
        self._layers = []
        num_dir = 2 if self.bidirectional else 1
        for i in range(num_layers):
            in_sz = input_size if i == 0 else hidden_size * num_dir
            if self.bidirectional:
                layer = BiRNN(self._cell_cls(in_sz, hidden_size,
                                             **cell_kwargs),
                              self._cell_cls(in_sz, hidden_size,
                                             **cell_kwargs),
                              time_major=time_major)
            else:
                layer = RNN(self._cell_cls(in_sz, hidden_size,
                                           **cell_kwargs),
                            time_major=time_major)
            self.add_sublayer(f"layer_{i}", layer)
            self._layers.append(layer)

    def _split_initial(self, initial_states):
        """Reference layout [num_layers * num_dirs, B, H] (tuple of two
        such for LSTM) -> per-layer state structures."""
        if initial_states is None:
            return [None] * self.num_layers
        num_dir = 2 if self.bidirectional else 1
        is_lstm = isinstance(initial_states, (tuple, list)) and \
            len(initial_states) == 2 and \
            getattr(initial_states[0], "ndim", 0) == 3

        def slab(stacked, idx):
            return stacked[idx]

        per_layer = []
        for i in range(self.num_layers):
            if is_lstm:
                h_all, c_all = initial_states
                if self.bidirectional:
                    per_layer.append((
                        (h_all[2 * i], c_all[2 * i]),
                        (h_all[2 * i + 1], c_all[2 * i + 1])))
                else:
                    per_layer.append((h_all[i], c_all[i]))
            else:
                st = initial_states
                if self.bidirectional:
                    per_layer.append((st[2 * i], st[2 * i + 1]))
                else:
                    per_layer.append(st[i])
        return per_layer

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..functional import dropout as F_dropout
        x = inputs
        finals = []
        per_layer_states = self._split_initial(initial_states)
        for i, layer in enumerate(self._layers):
            x, st = layer(x, per_layer_states[i], sequence_length)
            finals.append(st)
            if self.dropout and i < self.num_layers - 1 and self.training:
                x = F_dropout(x, p=self.dropout, training=True)
        return x, self._stack_finals(finals)

    def _stack_finals(self, finals):
        """[num_layers * num_directions, B, H] final states (reference
        layout)."""
        from ...framework.dispatch import call_op

        def flatten(f):
            if self.bidirectional:
                return [f[0], f[1]]
            return [f]

        per_dir = [g for f in finals for g in flatten(f)]
        if isinstance(per_dir[0], tuple):  # LSTM: (h, c) pairs
            hs = call_op("stack", [p[0] for p in per_dir], axis=0)
            cs = call_op("stack", [p[1] for p in per_dir], axis=0)
            return (hs, cs)
        return call_op("stack", per_dir, axis=0)


class SimpleRNN(RNNBase):
    _cell_cls = SimpleRNNCell

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation=activation)


class LSTM(RNNBase):
    _cell_cls = LSTMCell


class GRU(RNNBase):
    _cell_cls = GRUCell
