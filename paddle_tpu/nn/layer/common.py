"""Common layers: Linear, Embedding, Dropout, Flatten, padding, upsample.

Analog of the reference's ``python/paddle/nn/layer/common.py``.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.dtypes import convert_dtype
from .. import functional as F
from ..initializer import Constant, Normal, XavierUniform
from .layers import Layer, ParamAttr

__all__ = [
    "Fold", "PixelUnshuffle", "ChannelShuffle", "ZeroPad2D",
    "PairwiseDistance",
    "Identity", "Linear", "Embedding", "Dropout", "Dropout2D", "Dropout3D",
    "AlphaDropout", "Flatten", "Pad1D", "Pad2D", "Pad3D", "Upsample",
    "UpsamplingBilinear2D", "UpsamplingNearest2D", "CosineSimilarity",
    "PixelShuffle", "Unfold", "Bilinear",
]


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b with W stored [in_features, out_features] (the reference's
    layout, python/paddle/nn/layer/common.py Linear) — already the layout XLA
    wants for row-major activations hitting the MXU."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, " \
               f"out_features={self._out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 1.0))
        if padding_idx is not None:
            idx = padding_idx if padding_idx >= 0 \
                else num_embeddings + padding_idx
            self.weight._data = self.weight._data.at[idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis,
                         training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ...framework.dispatch import call_op
        return call_op("flatten", x, start_axis=self.start_axis,
                       stop_axis=self.stop_axis)


class _PadND(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad1D(_PadND):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadND):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadND):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size,
                             scale_factor=self.scale_factor, mode=self.mode,
                             align_corners=self.align_corners,
                             data_format=self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class Bilinear(Layer):
    """out[b, k] = x1[b, :] @ W[k] @ x2[b, :] + bias (reference
    nn/layer/common.py Bilinear)."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter([1, out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        from ...framework.dispatch import call_op
        return call_op("bilinear", x1, x2, self.weight, self.bias)


class Fold(Layer):
    """col2im (reference: nn/layer/common.py Fold)."""

    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self._a = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        o, k, s, p, d = self._a
        return F.fold(x, o, k, s, p, d)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._r = downscale_factor
        self._df = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self._r, self._df)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self._g = groups
        self._df = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self._g, self._df)


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self._p = padding
        self._df = data_format

    def forward(self, x):
        return F.zeropad2d(x, self._p, self._df)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self._p, self._eps, self._keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self._p, self._eps, self._keepdim)
