"""Transformer layers.

Analog of the reference's ``python/paddle/nn/layer/transformer.py``
(MultiHeadAttention, TransformerEncoderLayer/Encoder,
TransformerDecoderLayer/Decoder, Transformer — ~1.9k LoC of CUDA-era code).

TPU-native design: attention funnels through ONE op,
``scaled_dot_product_attention`` (nn/functional), so a Pallas flash-attention
kernel registered as an override accelerates every model built on these
layers. Weights stay in the reference's [in, out] layout feeding the MXU
directly; masks are additive float or boolean, broadcast [B, H, Lq, Lk].
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ...framework.dispatch import call_op
from ...framework.tensor import Tensor
from .. import functional as F
from .common import Dropout, Linear
from .container import LayerList
from .layers import Layer
from .norm import LayerNorm

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer",
           "TransformerEncoder", "TransformerDecoderLayer",
           "TransformerDecoder", "Transformer"]


def _convert_attn_mask(mask, dtype):
    """bool mask (True = keep) -> additive float mask."""
    if mask is None:
        return None
    import jax.numpy as jnp
    if mask.dtype == jnp.bool_:
        neg = jnp.asarray(-1e9 if dtype != jnp.float16 else -6e4, dtype)
        data = mask._data if isinstance(mask, Tensor) else mask
        return Tensor(jnp.where(data, jnp.asarray(0, dtype), neg))
    return mask


class MultiHeadAttention(Layer):
    """Reference: python/paddle/nn/layer/transformer.py MultiHeadAttention.

    Supports self- and cross-attention, optional incremental decode cache
    (the reference's ``Cache``/``StaticCache`` named tuples).
    """

    class Cache:
        def __init__(self, k, v):
            self.k, self.v = k, v

    class StaticCache:
        def __init__(self, k, v):
            self.k, self.v = k, v

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        if embed_dim % num_heads != 0:
            raise ValueError("embed_dim must be divisible by num_heads")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split_heads(self, x):
        # [B, L, E] -> [B, L, H, D]: the layout the sdpa op (and its Pallas
        # override) consumes — no transpose, XLA never materialises a copy.
        b, l = x.shape[0], x.shape[1]
        return call_op("reshape", x,
                       shape=(b, l, self.num_heads, self.head_dim))

    def _merge_heads(self, x):
        b, l, h, d = x.shape
        return call_op("reshape", x, shape=(b, l, h * d))

    def gen_cache(self, key, value=None, type=None):
        if type == MultiHeadAttention.StaticCache:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value if value is not None
                                              else key))
            return MultiHeadAttention.StaticCache(k, v)
        # incremental cache seeded empty ([B, 0, H, D], seq axis 1)
        import jax.numpy as jnp
        b = key.shape[0]
        k = Tensor(jnp.zeros((b, 0, self.num_heads, self.head_dim),
                             key._data.dtype))
        v = Tensor(jnp.zeros((b, 0, self.num_heads, self.head_dim),
                             key._data.dtype))
        return MultiHeadAttention.Cache(k, v)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        key = query if key is None else key
        value = key if value is None else value
        q = self._split_heads(self.q_proj(query))
        if isinstance(cache, MultiHeadAttention.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value))
            if isinstance(cache, MultiHeadAttention.Cache):
                k = call_op("concat", [cache.k, k], axis=1)  # seq axis
                v = call_op("concat", [cache.v, v], axis=1)
                cache = MultiHeadAttention.Cache(k, v)
        mask = _convert_attn_mask(attn_mask, q.dtype)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=mask,
            dropout_p=self.dropout if self.training else 0.0,
            training=self.training)
        out = self.out_proj(self._merge_heads(out))
        if cache is not None and not isinstance(
                cache, MultiHeadAttention.StaticCache):
            return out, cache
        return out


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = activation

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(
            call_op(self.activation, self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList(
            [encoder_layer if i == 0 else copy.deepcopy(encoder_layer)
             for i in range(num_layers)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, src_mask)
            else:
                output, c = layer(output, src_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = activation

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, new_inc = self.self_attn(tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        static = cache[1] if cache is not None else None
        tgt = self.cross_attn(tgt, memory, memory, memory_mask, static)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(
            call_op(self.activation, self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (new_inc, static))

    def gen_cache(self, memory):
        inc = self.self_attn.gen_cache(memory)
        static = self.cross_attn.gen_cache(
            memory, memory, type=MultiHeadAttention.StaticCache)
        return inc, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList(
            [decoder_layer if i == 0 else copy.deepcopy(decoder_layer)
             for i in range(num_layers)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, memory, tgt_mask, memory_mask)
            else:
                output, c = layer(output, memory, tgt_mask, memory_mask,
                                  cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        caches = [layer.gen_cache(memory) for layer in self.layers]
        return list(zip(*caches)) if do_zip else caches


class Transformer(Layer):
    """Full encoder-decoder transformer (reference Transformer)."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import jax.numpy as jnp
        return Tensor(jnp.where(
            jnp.tril(jnp.ones((length, length), jnp.bool_)), 0.0, -1e9
        ).astype(jnp.float32))
