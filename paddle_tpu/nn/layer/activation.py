"""Activation layers — thin Layer wrappers over nn.functional.

Analog of the reference's ``python/paddle/nn/layer/activation.py``.
"""
from __future__ import annotations

from ..initializer import Constant
from .. import functional as F
from .layers import Layer

__all__ = [
    "Softmax2D",
    "ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "Softmax", "LogSoftmax",
    "LeakyReLU", "ELU", "CELU", "SELU", "Silu", "Swish", "Mish",
    "Hardsigmoid", "Hardswish", "Hardtanh", "Hardshrink", "Softshrink",
    "Softplus", "Softsign", "Tanhshrink", "ThresholdedReLU", "LogSigmoid",
    "Maxout", "PReLU", "RReLU", "GLU",
]


def _wrap(name, fname=None, **fixed):
    fname = fname or name.lower()

    class _Act(Layer):
        def __init__(self, name=None):
            super().__init__()

        def forward(self, x):
            return getattr(F, fname)(x, **fixed)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _wrap("ReLU", "relu")
ReLU6 = _wrap("ReLU6", "relu6")
Sigmoid = _wrap("Sigmoid", "sigmoid")
Tanh = _wrap("Tanh", "tanh")
Silu = _wrap("Silu", "silu")
Swish = _wrap("Swish", "swish")
Mish = _wrap("Mish", "mish")
Hardswish = _wrap("Hardswish", "hardswish")
Softsign = _wrap("Softsign", "softsign")
Tanhshrink = _wrap("Tanhshrink", "tanhshrink")
LogSigmoid = _wrap("LogSigmoid", "log_sigmoid")


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self._approximate = approximate

    def forward(self, x):
        return F.gelu(x, approximate=self._approximate)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self._axis)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.elu(x, self._alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.celu(x, self._alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772,
                 name=None):
        super().__init__()
        self._scale, self._alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self._scale, self._alpha)


class Hardsigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.hardsigmoid(x)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self._min, self._max = min, max

    def forward(self, x):
        return F.hardtanh(x, self._min, self._max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self._threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self._threshold)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self._beta, self._threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self._beta, self._threshold)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.thresholded_relu(x, self._threshold)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups, self._axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self._data_format)


class RReLU(Layer):
    def __init__(self, lower=1. / 8., upper=1. / 3., name=None):
        super().__init__()
        self._lower, self._upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self._lower, self._upper, training=self.training)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.glu(x, self._axis)


class Softmax2D(Layer):
    """Softmax over the channel axis of NCHW input (reference:
    nn/layer/activation.py Softmax2D)."""

    def forward(self, x):
        return F.softmax(x, axis=-3)
