"""Pooling layers — thin wrappers over nn.functional pooling.

Analog of the reference's ``python/paddle/nn/layer/pooling.py``.
"""
from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = ["MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D", "AdaptiveMaxPool3D",
           "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D", "AvgPool2D",
           "AvgPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D"]


class _PoolNd(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 **kw):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self._kw = kw

    def extra_repr(self):
        return (f"kernel_size={self.kernel_size}, stride={self.stride}, "
                f"padding={self.padding}")


class MaxPool1D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode)
        self.return_mask = return_mask

    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            return_mask=self.return_mask,
                            ceil_mode=self.ceil_mode)


class MaxPool2D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, data_format="NCHW",
                 name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode)
        self.return_mask = return_mask
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            return_mask=self.return_mask,
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class MaxPool3D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, data_format="NCDHW",
                 name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode)
        self.return_mask = return_mask
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            return_mask=self.return_mask,
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class AvgPool1D(_PoolNd):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class AvgPool2D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode)
        self.exclusive = exclusive
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode,
                            exclusive=self.exclusive,
                            data_format=self.data_format)


class AvgPool3D(_PoolNd):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self._output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._output_size,
                                     self._data_format)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self._output_size,
                                     self._data_format)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self._output_size,
                                     self._return_mask)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self._output_size,
                                     self._return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self._output_size,
                                     self._return_mask)


class _MaxUnPoolNd(Layer):
    _fn = None

    _default_df = None

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        super().__init__()
        self._k, self._s, self._p = kernel_size, stride, padding
        self._output_size = output_size
        self._df = data_format if data_format is not None \
            else type(self)._default_df

    def forward(self, x, indices):
        return type(self)._fn(x, indices, self._k, self._s, self._p,
                              self._df, self._output_size)


class MaxUnPool1D(_MaxUnPoolNd):
    _fn = staticmethod(F.max_unpool1d)
    _default_df = "NCL"


class MaxUnPool2D(_MaxUnPoolNd):
    _fn = staticmethod(F.max_unpool2d)
    _default_df = "NCHW"


class MaxUnPool3D(_MaxUnPoolNd):
    _fn = staticmethod(F.max_unpool3d)
    _default_df = "NCDHW"
