"""Normalization layers.

Analog of the reference's ``python/paddle/nn/layer/norm.py``. BatchNorm's
running stats are registered buffers updated functionally: the batch_norm op
returns (y, new_mean, new_var) and the layer writes the buffers, which the
``functional_state`` bridge captures for jitted training steps — the
TPU-native replacement for in-place CUDA updates.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.dispatch import call_op
from ...framework.tensor import Tensor
from .. import functional as F
from ..initializer import Constant
from .layers import Layer

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm",
           "RMSNorm", "SpectralNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        self.register_buffer(
            "_mean", Tensor(jnp.zeros([num_features], jnp.float32)))
        self.register_buffer(
            "_variance", Tensor(jnp.ones([num_features], jnp.float32)))

    def forward(self, x):
        training = self.training and not (self._use_global_stats or False)
        y, new_mean, new_var = call_op(
            "batch_norm", x, self._mean, self._variance, self.weight,
            self.bias, training=training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format)
        if training:
            # mutate ._data in place (not the buffer objects) so the
            # functional_state bridge can capture & restore the values
            self._buffers["_mean"]._data = new_mean._data
            self._buffers["_variance"]._data = new_var._data
        return y

    def extra_repr(self):
        return f"num_features={self._num_features}, " \
               f"momentum={self._momentum}, epsilon={self._epsilon}"


class BatchNorm(_BatchNormBase):
    """Legacy-style ctor (reference fluid.dygraph.BatchNorm)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, data_layout="NCHW",
                 use_global_stats=False, **kwargs):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats=use_global_stats)
        self._act = act

    def forward(self, x):
        y = super().forward(x)
        if self._act:
            y = call_op(self._act, y)
        return y


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BatchNorm (reference nn/layer/norm.py SyncBatchNorm,
    backed by sync_batch_norm CUDA kernel + NCCL).

    TPU-native: under pjit/GSPMD the batch axis is sharded across the mesh
    and ``batch_norm``'s mean/var reductions automatically become cross-chip
    psums when the input is batch-sharded — so plain batch_norm IS sync BN
    inside a sharded train step. This class exists for API parity; eager
    single-chip behavior equals BatchNorm.
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon,
                                data_format=layer._data_format)
            new.weight = layer.weight
            new.bias = layer.bias
            new._buffers["_mean"] = layer._mean
            new._buffers["_variance"] = layer._variance
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter(self._normalized_shape,
                                          attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, " \
               f"epsilon={self._epsilon}"


class RMSNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            list(normalized_shape), attr=weight_attr,
            default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k)


class SpectralNorm(Layer):
    """Power-iteration spectral normalization of a weight tensor
    (reference nn/layer/norm.py SpectralNorm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        from ..initializer import Normal
        self.weight_u = self.create_parameter(
            [h], default_initializer=Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        return call_op("spectral_norm", weight, self.weight_u, self.weight_v,
                       dim=self._dim, power_iters=self._power_iters,
                       eps=self._eps)
