"""Layer base class.

Analog of the reference's ``paddle.nn.Layer``
(/root/reference/python/paddle/fluid/dygraph/layers.py): parameter/sublayer
registration, hooks, state_dict, train/eval mode, ``to()`` dtype moves.

TPU-native addition: :func:`functional_state` — temporarily swap a pytree of
arrays into the layer's parameters/buffers so a pure ``fn(params, batch)``
can be traced by ``jax.jit``/``jax.grad``. This is the bridge between the
stateful dygraph API and jax's functional transforms (replacing the
reference's dygraph→static ProgramTranslator for the common training path).
"""
from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.dtypes import convert_dtype, get_default_dtype
from ...framework.tensor import Parameter, Tensor, no_grad_guard


class ParamAttr:
    """Analog of paddle.ParamAttr (python/paddle/fluid/param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if attr is False:
            return False
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        # an Initializer instance
        return ParamAttr(initializer=attr)


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    """Base class for all network layers."""

    def __init__(self, name_scope=None, dtype=None):
        self._dtype = convert_dtype(dtype) if dtype else get_default_dtype()
        self._parameters: "OrderedDict[str, Parameter]" = OrderedDict()
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._buffers: "OrderedDict[str, Tensor]" = OrderedDict()
        self._non_persistable_buffer_names = set()
        self.training = True
        self._forward_pre_hooks: Dict[int, Callable] = OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = OrderedDict()
        self._next_hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- attribute magic ----------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError(
                    "call super().__init__() before assigning parameters")
            for d in (layers, buffers):
                d.pop(name, None) if d else None
            params[name] = value
            object.__getattribute__(self, "__dict__").pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError(
                    "call super().__init__() before assigning sublayers")
            layers[name] = value
            object.__getattribute__(self, "__dict__").pop(name, None)
        elif buffers is not None and name in buffers:
            if value is None:
                buffers[name] = None
            else:
                buffers[name] = value if isinstance(value, Tensor) \
                    else Tensor(jnp.asarray(value))
        else:
            if params is not None and name in params:
                if value is None or isinstance(value, Tensor):
                    params.pop(name)
                    if value is not None:
                        object.__setattr__(self, name, value)
                    return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # -- registration -------------------------------------------------------
    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None,
                         is_bias=False, default_initializer=None):
        from ..initializer import Constant, XavierUniform, \
            _global_initializer
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = convert_dtype(dtype) if dtype else self._dtype
        # priority (reference set_global_initializer contract): explicit
        # ParamAttr > global default > the layer's built-in default
        init = attr.initializer or _global_initializer(is_bias) or \
            default_initializer or \
            (Constant(0.0) if is_bias else XavierUniform())
        data = init(tuple(shape), dtype)
        p = Parameter(data, name=attr.name, trainable=attr.trainable)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    # -- iteration ----------------------------------------------------------
    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for _, layer_prefix, layer in self._walk(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield ((layer_prefix + "." + pname if layer_prefix
                        else pname), p)

    def _walk(self, prefix="", include_sublayers=True):
        yield None, prefix, self
        if include_sublayers:
            for sname, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = prefix + "." + sname if prefix else sname
                yield from sub._walk(sub_prefix, True)

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for sname, sub in self._sub_layers.items():
            if sub is None:
                continue
            p = prefix + "." + sname if prefix else sname
            yield p, sub
            yield from sub.named_sublayers(p)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter((n, l) for n, l in self._sub_layers.items()
                    if l is not None)

    def named_buffers(self, prefix="", include_sublayers=True):
        for _, layer_prefix, layer in self._walk(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None:
                    continue
                yield ((layer_prefix + "." + bname if layer_prefix
                        else bname), b)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    # -- mode ---------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._next_hook_id += 1
        self._forward_pre_hooks[self._next_hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._next_hook_id)

    def register_forward_post_hook(self, hook):
        self._next_hook_id += 1
        self._forward_post_hooks[self._next_hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._next_hook_id)

    # -- call ---------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   use_hook=True):
        dest = OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(
                include_sublayers=include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(
                include_sublayers=include_sublayers):
            short = name.rsplit(".", 1)[-1]
            owner = self._locate_owner(name)
            if owner is not None and \
                    short in owner._non_persistable_buffer_names:
                continue
            dest[name] = b
        return dest

    def _locate_owner(self, qualified_name):
        parts = qualified_name.split(".")[:-1]
        layer = self
        for p in parts:
            layer = layer._sub_layers.get(p)
            if layer is None:
                return None
        return layer

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, target in own.items():
            if name in state_dict:
                value = state_dict[name]
                arr = value._data if isinstance(value, Tensor) \
                    else jnp.asarray(value)
                if tuple(arr.shape) != tuple(target._data.shape):
                    raise ValueError(
                        f"shape mismatch for {name}: {arr.shape} vs "
                        f"{target._data.shape}")
                target._data = arr.astype(target._data.dtype)
            else:
                missing.append(name)
        for k in state_dict:
            if k not in own:
                unexpected.append(k)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- dtype / device -----------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = convert_dtype(dtype)
            for p in self.parameters():
                if jnp.issubdtype(p._data.dtype, jnp.floating):
                    p._data = p._data.astype(dt)
            for b in self.buffers():
                if jnp.issubdtype(b._data.dtype, jnp.floating):
                    b._data = b._data.astype(dt)
            for l in self.sublayers(include_self=True):
                l._dtype = dt
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = "\n  ".join(sub_repr)
            lines.append(f"({name}): {sub_repr}")
        body = ",\n  ".join(lines)
        if body:
            return f"{type(self).__name__}({extra}\n  {body}\n)"
        return f"{type(self).__name__}({extra})"


# ---------------------------------------------------------------------------
# functional bridge (jit/grad over stateful Layers)
# ---------------------------------------------------------------------------

def get_params_tree(layer: Layer) -> Dict[str, jnp.ndarray]:
    return {name: p._data for name, p in layer.named_parameters()}


def get_buffers_tree(layer: Layer) -> Dict[str, jnp.ndarray]:
    return {name: b._data for name, b in layer.named_buffers()}


@contextlib.contextmanager
def functional_state(layer: Layer, params: Dict[str, jnp.ndarray],
                     buffers: Optional[Dict[str, jnp.ndarray]] = None):
    """Swap arrays into the layer, yield, restore; collect buffer updates.

    Inside the context the layer's parameters/buffers hold (possibly traced)
    arrays from ``params``/``buffers``. On exit, ``updated_buffers`` holds
    the final buffer values (e.g. BN running stats written during forward).
    """
    param_objs = dict(layer.named_parameters())
    buffer_objs = dict(layer.named_buffers())
    old_params = {k: p._data for k, p in param_objs.items()}
    old_buffers = {k: b._data for k, b in buffer_objs.items()}
    result = {}
    try:
        for k, arr in params.items():
            if k in param_objs:
                param_objs[k]._data = arr
        if buffers:
            for k, arr in buffers.items():
                if k in buffer_objs:
                    buffer_objs[k]._data = arr
        yield result
        result["updated_buffers"] = {
            k: b._data for k, b in layer.named_buffers()}
    finally:
        for k, p in param_objs.items():
            p._data = old_params[k]
        for k, b in buffer_objs.items():
            b._data = old_buffers[k]


def functional_call(layer: Layer, params, buffers, *inputs, **kwargs):
    """Pure functional forward: returns (outputs, updated_buffers).

    Gradient tape is disabled inside — jax.grad provides autodiff on the
    functional path, so tape recording would only waste memory.
    """
    with functional_state(layer, params, buffers) as st:
        with no_grad_guard():
            wrapped = [Tensor(x, stop_gradient=True)
                       if isinstance(x, (jax.Array, jnp.ndarray, np.ndarray))
                       and not isinstance(x, Tensor) else x for x in inputs]
            out = layer(*wrapped, **kwargs)
    return out, st["updated_buffers"]
