"""Loss layers.

Analog of the reference's ``python/paddle/nn/layer/loss.py``.
"""
from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = ["CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
           "BCEWithLogitsLoss", "KLDivLoss", "SmoothL1Loss", "HuberLoss",
           "MarginRankingLoss", "HingeEmbeddingLoss", "SigmoidFocalLoss",
           "CTCLoss", "HSigmoidLoss", "CosineEmbeddingLoss",
           "TripletMarginLoss", "TripletMarginWithDistanceLoss",
           "MultiLabelSoftMarginLoss"]


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self._weight = weight
        self._ignore_index = ignore_index
        self._reduction = reduction
        self._soft_label = soft_label
        self._axis = axis
        self._use_softmax = use_softmax
        self._label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(
            input, label, weight=self._weight,
            ignore_index=self._ignore_index, reduction=self._reduction,
            soft_label=self._soft_label, axis=self._axis,
            use_softmax=self._use_softmax,
            label_smoothing=self._label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self._reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self._reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self._weight = weight
        self._ignore_index = ignore_index
        self._reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self._weight, self._ignore_index,
                          self._reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._weight = weight
        self._reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self._weight,
                                      self._reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self._weight = weight
        self._reduction = reduction
        self._pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self._weight, self._reduction, self._pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self._reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self._reduction = reduction
        self._delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self._reduction, self._delta)


class HuberLoss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self._reduction = reduction
        self._delta = delta

    def forward(self, input, label):
        return F.huber_loss(input, label, self._delta, self._reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self._margin = margin
        self._reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self._margin,
                                     self._reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self._margin = margin
        self._reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self._margin,
                                      self._reduction)


class SigmoidFocalLoss(Layer):
    def __init__(self, alpha=0.25, gamma=2.0, normalizer=None,
                 reduction="sum", name=None):
        super().__init__()
        self._alpha = alpha
        self._gamma = gamma
        self._normalizer = normalizer
        self._reduction = reduction

    def forward(self, logit, label):
        return F.sigmoid_focal_loss(logit, label, self._normalizer,
                                    self._alpha, self._gamma,
                                    self._reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self._blank = blank
        self._reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self._blank, self._reduction, norm_by_times)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid (reference: nn/layer/loss.py HSigmoidLoss) —
    holds the [num_classes-1, feature] internal-node weights."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self._num_classes = num_classes
        n_nodes = num_classes - 1
        self.weight = self.create_parameter(
            [n_nodes, feature_size], attr=weight_attr)
        self.bias = self.create_parameter(
            [n_nodes], attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self._num_classes, self.weight,
                               self.bias, path_table, path_code)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self._margin, self._reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self._margin,
                                       self._reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._a = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        m, p, e, s, r = self._a
        return F.triplet_margin_loss(input, positive, negative, m, p, e, s, r)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._a = (distance_function, margin, swap, reduction)

    def forward(self, input, positive, negative):
        d, m, s, r = self._a
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, d, m, s, r)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._weight, self._reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self._weight,
                                              self._reduction)
