"""``paddle.nn.functional`` — functional neural-net ops.

Analog of the reference's ``python/paddle/nn/functional/`` (activation.py,
common.py, conv.py, loss.py, norm.py, pooling.py, input.py). Every function
dispatches through the op registry (framework/dispatch.py), so the same code
runs eagerly and under jit tracing; XLA fuses what the reference hand-fused.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...framework import random as _random
from ...framework.dispatch import call_op as _op
from ...framework.tensor import Tensor

__all__ = []


def _export(fn):
    __all__.append(fn.__name__)
    return fn


# ---------------------------------------------------------------------------
# activations (reference: python/paddle/nn/functional/activation.py)
# ---------------------------------------------------------------------------

def _simple(name):
    def fn(x, name=None):
        return _op(name_, x)
    name_ = name
    fn.__name__ = name
    return _export(fn)


relu = _simple("relu")
relu6 = _simple("relu6")
sigmoid = _simple("sigmoid")
tanh = _simple("tanh")
silu = _simple("silu")
swish = _simple("silu")
mish = _simple("mish")
tanhshrink = _simple("tanhshrink")
log_sigmoid = _simple("log_sigmoid")
hardswish = _simple("hardswish")
softsign = _simple("softsign")


@_export
def gelu(x, approximate=False, name=None):
    return _op("gelu", x, approximate=approximate)


@_export
def leaky_relu(x, negative_slope=0.01, name=None):
    return _op("leaky_relu", x, negative_slope=negative_slope)


@_export
def elu(x, alpha=1.0, name=None):
    return _op("elu", x, alpha=alpha)


@_export
def celu(x, alpha=1.0, name=None):
    return _op("celu", x, alpha=alpha)


@_export
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _op("selu", x, scale=scale, alpha=alpha)


@_export
def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return _op("hardsigmoid", x, slope=slope, offset=offset)


@_export
def hardtanh(x, min=-1.0, max=1.0, name=None):
    return _op("hardtanh", x, min=min, max=max)


@_export
def hardshrink(x, threshold=0.5, name=None):
    return _op("hardshrink", x, threshold=threshold)


@_export
def softshrink(x, threshold=0.5, name=None):
    return _op("softshrink", x, threshold=threshold)


@_export
def softplus(x, beta=1.0, threshold=20.0, name=None):
    return _op("softplus", x, beta=beta, threshold=threshold)


@_export
def thresholded_relu(x, threshold=1.0, name=None):
    return _op("thresholded_relu", x, threshold=threshold)


@_export
def prelu(x, weight, data_format="NCHW", name=None):
    return _op("prelu", x, weight)


@_export
def rrelu(x, lower=1. / 8., upper=1. / 3., training=True, name=None):
    return _op("rrelu", x, _random.next_key(), lower=lower, upper=upper,
               training=training)


@_export
def softmax(x, axis=-1, dtype=None, name=None):
    out = _op("softmax", x, axis=axis)
    if dtype is not None:
        out = _op("cast", out, dtype=dtype)
    return out


@_export
def log_softmax(x, axis=-1, dtype=None, name=None):
    out = _op("log_softmax", x, axis=axis)
    if dtype is not None:
        out = _op("cast", out, dtype=dtype)
    return out


@_export
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    return _op("gumbel_softmax", x, _random.next_key(),
               temperature=temperature, hard=hard, axis=axis)


@_export
def maxout(x, groups, axis=1, name=None):
    return _op("maxout", x, groups=groups, axis=axis)


@_export
def glu(x, axis=-1, name=None):
    return _op("glu", x, axis=axis)


# ---------------------------------------------------------------------------
# common (reference: python/paddle/nn/functional/common.py)
# ---------------------------------------------------------------------------

@_export
def linear(x, weight, bias=None, name=None):
    return _op("linear", x, weight, bias)


@_export
def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        # reference semantics: downscale_in_infer scales by (1-p) at eval
        if not training and mode == "downscale_in_infer" and p > 0.0:
            return _op("scale", x, scale=1.0 - float(p))
        return x if isinstance(x, Tensor) else _op("assign", x)
    axis_attr = None if axis is None else tuple(
        (axis,) if isinstance(axis, int) else tuple(int(a) for a in axis))
    return _op("dropout_raw", x, _random.next_key(), p=float(p),
               axis=axis_attr, mode=mode)


@_export
def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


@_export
def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


@_export
def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    return _op("alpha_dropout", x, _random.next_key(), p=float(p))


@_export
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if not isinstance(pad, (list, tuple)):
        pad = np.asarray(pad).tolist()
    return _op("pad", x, pad=tuple(int(p) for p in pad), mode=mode,
               value=value, data_format=data_format)


@_export
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    if isinstance(size, Tensor):
        size = [int(v) for v in np.asarray(size._data)]
    elif size is not None and not isinstance(size, (list, tuple)):
        size = [int(size)]
    elif size is not None:
        size = [int(s._data) if isinstance(s, Tensor) else int(s)
                for s in size]
    return _op("interpolate", x, size=tuple(size) if size else None,
               scale_factor=tuple(scale_factor)
               if isinstance(scale_factor, (list, tuple))
               else scale_factor,
               mode=mode, align_corners=align_corners,
               data_format=data_format)


upsample = _export(lambda x, size=None, scale_factor=None, mode="nearest", \
    align_corners=False, align_mode=0, data_format="NCHW", name=None: \
    interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                data_format))
upsample.__name__ = "upsample"


@_export
def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    return _op("embedding", x, weight, padding_idx=padding_idx)


@_export
def one_hot(x, num_classes, name=None):
    return _op("one_hot", x, num_classes=num_classes)


@_export
def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    return _op("label_smooth", label, prior_dist, epsilon=epsilon)


@_export
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    return _op("unfold", x, kernel_sizes=kernel_sizes, strides=strides,
               paddings=paddings, dilations=dilations)


@_export
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    return _op("cosine_similarity", x1, x2, axis=axis, eps=eps)


@_export
def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    return _op("pixel_shuffle", x, upscale_factor=upscale_factor,
               data_format=data_format)


@_export
def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return _op("normalize_l2", x, p=float(p), axis=axis, epsilon=epsilon)


@_export
def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    return _op("sequence_mask", x, maxlen=maxlen, dtype=dtype)


# ---------------------------------------------------------------------------
# variable-length sequence ops over the dense (padded, lengths) encoding —
# the TPU-native LoD replacement (ops/sequence_ops.py; reference:
# paddle/fluid/operators/sequence_ops/)
# ---------------------------------------------------------------------------

@_export
def sequence_pad(x, lengths, pad_value=0.0, maxlen=None, name=None):
    return _op("sequence_pad", x, lengths, pad_value=pad_value,
               maxlen=maxlen)


@_export
def sequence_unpad(x, lengths, total_length=None, name=None):
    return _op("sequence_unpad", x, lengths, total_length=total_length)


@_export
def sequence_pool(x, lengths, pool_type="sum", name=None):
    return _op("sequence_pool", x, lengths, pool_type=pool_type)


@_export
def sequence_softmax(x, lengths, name=None):
    return _op("sequence_softmax", x, lengths)


@_export
def sequence_reverse(x, lengths, name=None):
    return _op("sequence_reverse", x, lengths)


@_export
def sequence_expand(x, ref_lengths, maxlen=None, name=None):
    return _op("sequence_expand", x, ref_lengths, maxlen=maxlen)


@_export
def sequence_slice(x, lengths, offset, length, maxlen=None, name=None):
    return _op("sequence_slice", x, lengths, offset, length, maxlen=maxlen)


@_export
def sequence_enumerate(ids, lengths, win_size, pad_value=0, name=None):
    return _op("sequence_enumerate", ids, lengths, win_size=win_size,
               pad_value=pad_value)


@_export
def sequence_concat(xs, lengths_list, maxlen=None, name=None):
    return _op("sequence_concat", xs, lengths_list, maxlen=maxlen)


@_export
def sequence_conv(x, lengths, weight, bias=None, context_length=3,
                  context_start=None, pad_value=0.0, name=None):
    return _op("sequence_conv", x, lengths, weight, bias,
               context_length=context_length, context_start=context_start,
               pad_value=pad_value)


# ---------------------------------------------------------------------------
# conv / pooling (reference: conv.py, pooling.py)
# ---------------------------------------------------------------------------

@_export
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _op("conv1d", x, weight, bias, stride=stride, padding=padding,
               dilation=dilation, groups=groups, data_format=data_format)


@_export
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _op("conv2d", x, weight, bias, stride=stride, padding=padding,
               dilation=dilation, groups=groups, data_format=data_format)


@_export
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _op("conv3d", x, weight, bias, stride=stride, padding=padding,
               dilation=dilation, groups=groups, data_format=data_format)


@_export
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    if output_size is not None:
        output_padding = _opad_from_output_size(
            x, weight, stride, padding, dilation, output_size, 2,
            data_format)
    return _op("conv2d_transpose", x, weight, bias, stride=stride,
               padding=padding, output_padding=output_padding, groups=groups,
               dilation=dilation, output_size=output_size,
               data_format=data_format)


@_export
def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    if return_mask:
        return _op("max_pool1d_with_mask", x, kernel_size=kernel_size,
                   stride=stride, padding=padding, ceil_mode=ceil_mode)
    return _op("max_pool1d", x, kernel_size=kernel_size, stride=stride,
               padding=padding, ceil_mode=ceil_mode)


@_export
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _op("avg_pool1d", x, kernel_size=kernel_size, stride=stride,
               padding=padding, ceil_mode=ceil_mode, exclusive=exclusive)


@_export
def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        if not data_format.startswith("NC"):
            raise ValueError(
                "return_mask=True requires NCHW (reference restriction)")
        return _op("max_pool2d_with_mask", x, kernel_size=kernel_size,
                   stride=stride, padding=padding, ceil_mode=ceil_mode)
    return _op("max_pool2d", x, kernel_size=kernel_size, stride=stride,
               padding=padding, ceil_mode=ceil_mode, data_format=data_format)


@_export
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _op("avg_pool2d", x, kernel_size=kernel_size, stride=stride,
               padding=padding, ceil_mode=ceil_mode, exclusive=exclusive,
               data_format=data_format)


@_export
def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        if not data_format.startswith("NC"):
            raise ValueError(
                "return_mask=True requires NCDHW (reference restriction)")
        return _op("max_pool3d_with_mask", x, kernel_size=kernel_size,
                   stride=stride, padding=padding, ceil_mode=ceil_mode)
    return _op("max_pool3d", x, kernel_size=kernel_size, stride=stride,
               padding=padding, ceil_mode=ceil_mode, data_format=data_format)


@_export
def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _op("avg_pool3d", x, kernel_size=kernel_size, stride=stride,
               padding=padding, ceil_mode=ceil_mode, exclusive=exclusive,
               data_format=data_format)


@_export
def adaptive_avg_pool1d(x, output_size, name=None):
    return _op("adaptive_avg_pool1d", x, output_size=output_size)


@_export
def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_with_mask(x, output_size, 1)
    return _op("adaptive_max_pool1d", x, output_size=output_size)


@_export
def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _op("adaptive_avg_pool2d", x, output_size=output_size,
               data_format=data_format)


@_export
def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_with_mask(x, output_size, 2)
    return _op("adaptive_max_pool2d", x, output_size=output_size)


def _adaptive_max_with_mask(x, output_size, nd):
    out = (output_size,) * nd if isinstance(output_size, int) \
        else tuple(output_size)
    spatial = x.shape[2:2 + nd]
    if any(s % o != 0 for s, o in zip(spatial, out)):
        raise NotImplementedError(
            "return_mask=True needs output_size dividing the input size")
    ks = tuple(s // o for s, o in zip(spatial, out))
    return _op(f"max_pool{nd}d_with_mask", x, kernel_size=ks, stride=ks,
               padding=0)


# ---------------------------------------------------------------------------
# norms (reference: norm.py)
# ---------------------------------------------------------------------------

@_export
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, (list, tuple)):
        n_norm = len(normalized_shape)
    else:
        n_norm = 1
    return _op("layer_norm", x, weight, bias, epsilon=epsilon,
               begin_norm_axis=len(x.shape) - n_norm)


@_export
def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    return _op("batch_norm", x, running_mean, running_var, weight, bias,
               training=training if use_global_stats is None
               else not use_global_stats,
               momentum=momentum, epsilon=epsilon, data_format=data_format)


@_export
def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9,
                  eps=1e-5, data_format="NCHW", name=None):
    return _op("instance_norm", x, weight, bias, epsilon=eps)


@_export
def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    return _op("group_norm", x, weight, bias, epsilon=epsilon,
               num_groups=num_groups, data_format=data_format)


@_export
def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    return _op("rms_norm", x, weight, epsilon=epsilon)


@_export
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    return _op("local_response_norm", x, size=size, alpha=alpha, beta=beta,
               k=k)


# ---------------------------------------------------------------------------
# losses (reference: loss.py)
# ---------------------------------------------------------------------------

@_export
def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    return _op("cross_entropy", input, label, weight,
               soft_label=soft_label, axis=axis, ignore_index=ignore_index,
               reduction=reduction, use_softmax=use_softmax,
               label_smoothing=label_smoothing)


@_export
def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    return _op("softmax_with_cross_entropy", logits, label,
               soft_label=soft_label, axis=axis, ignore_index=ignore_index,
               return_softmax=return_softmax)


@_export
def mse_loss(input, label, reduction="mean", name=None):
    return _op("mse_loss", input, label, reduction=reduction)


@_export
def l1_loss(input, label, reduction="mean", name=None):
    return _op("l1_loss", input, label, reduction=reduction)


@_export
def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    return _op("smooth_l1_loss", input, label, reduction=reduction,
               delta=delta)


@_export
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    return _op("nll_loss", input, label, weight, ignore_index=ignore_index,
               reduction=reduction)


@_export
def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    return _op("bce_loss", input, label, weight, reduction=reduction)


@_export
def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    return _op("bce_with_logits", logit, label, weight, pos_weight,
               reduction=reduction)


@_export
def kl_div(input, label, reduction="mean", name=None):
    return _op("kl_div", input, label, reduction=reduction)


@_export
def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    return _op("hinge_embedding_loss", input, label, margin=margin,
               reduction=reduction)


@_export
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    return _op("margin_ranking_loss", input, other, label, margin=margin,
               reduction=reduction)


@_export
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    return _op("sigmoid_focal_loss", logit, label, normalizer, alpha=alpha,
               gamma=gamma, reduction=reduction)


@_export
def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    return _op("huber_loss", input, label, delta=delta, reduction=reduction)


@_export
def square_error_cost(input, label):
    d = _op("subtract", input, label)
    return _op("multiply", d, d)


# ---------------------------------------------------------------------------
# attention (reference: fused_attention / sparse_attention; TPU-native flash
# attention lives behind this one entry point via a Pallas override)
# ---------------------------------------------------------------------------

@_export
def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Inputs are [batch, seq, num_heads, head_dim] (the reference's
    fused-attention layout)."""
    key_rng = _random.next_key() if (dropout_p > 0.0 and training) else None
    return _op("scaled_dot_product_attention", query, key, value, attn_mask,
               key_rng, dropout_p=dropout_p if training else 0.0,
               is_causal=is_causal)


@_export
def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """CSR-patterned attention (reference:
    python/paddle/nn/functional/sparse_attention.py). The CSR pattern is
    materialised as a dense mask — on TPU the masked-dense form rides the
    MXU and is the fast path at the block sparsities the reference supports."""
    return _op("sparse_attention", query, key, value, sparse_csr_offset,
               sparse_csr_columns, key_padding_mask, attn_mask)


# ---------------------------------------------------------------------------
# transposed convs / 3-D adaptive pooling / unpooling (reference: conv.py,
# pooling.py)
# ---------------------------------------------------------------------------

@_export
def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    if output_size is not None:
        output_padding = _opad_from_output_size(
            x, weight, stride, padding, dilation, output_size, 1,
            data_format)
    return _op("conv1d_transpose", x, weight, bias, stride=stride,
               padding=padding, output_padding=output_padding, groups=groups,
               dilation=dilation, data_format=data_format)


def _opad_from_output_size(x, weight, stride, padding, dilation,
                           output_size, nd, data_format="NC"):
    """output_size -> output_padding (reference: conv_transpose derives the
    extra high-side padding from the requested spatial size)."""
    def tup(v):
        return (int(v),) * nd if isinstance(v, int) else \
            tuple(int(i) for i in v)
    st, dl = tup(stride), tup(dilation)
    pd = tup(padding) if not isinstance(padding, (list, tuple)) or \
        all(isinstance(p, int) for p in padding) else None
    if pd is None:
        raise ValueError("output_size with per-side padding is unsupported")
    if isinstance(padding, int):
        pd = (padding,) * nd
    target = [int(v) for v in output_size][-nd:]
    in_sp = x.shape[2:2 + nd] if data_format.startswith("NC") \
        else x.shape[1:1 + nd]
    ks = weight.shape[2:2 + nd]
    opad = []
    for d in range(nd):
        base = (in_sp[d] - 1) * st[d] - 2 * pd[d] + dl[d] * (ks[d] - 1) + 1
        op = target[d] - base
        if not 0 <= op < st[d] + dl[d]:
            raise ValueError(
                f"invalid output_size {target[d]} for dim {d}: base {base}")
        opad.append(op)
    return tuple(opad)


@_export
def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    if output_size is not None:
        output_padding = _opad_from_output_size(
            x, weight, stride, padding, dilation, output_size, 3,
            data_format)
    return _op("conv3d_transpose", x, weight, bias, stride=stride,
               padding=padding, output_padding=output_padding, groups=groups,
               dilation=dilation, data_format=data_format)


@_export
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _op("adaptive_avg_pool3d", x, output_size=output_size,
               data_format=data_format)


@_export
def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_with_mask(x, output_size, 3)
    return _op("adaptive_max_pool3d", x, output_size=output_size)


@_export
def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    if not data_format.startswith("NC"):
        raise ValueError(
            "max_unpool1d supports channel-first only "
            "(reference restriction)")
    return _op("max_unpool1d", x, indices, kernel_size=kernel_size,
               stride=stride, padding=padding, output_size=output_size)


@_export
def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    if not data_format.startswith("NC"):
        raise ValueError(
            "max_unpool2d supports channel-first only "
            "(reference restriction)")
    return _op("max_unpool2d", x, indices, kernel_size=kernel_size,
               stride=stride, padding=padding, output_size=output_size)


@_export
def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    if not data_format.startswith("NC"):
        raise ValueError(
            "max_unpool3d supports channel-first only "
            "(reference restriction)")
    return _op("max_unpool3d", x, indices, kernel_size=kernel_size,
               stride=stride, padding=padding, output_size=output_size)


# ---------------------------------------------------------------------------
# rearrangement / sampling / video (reference: vision.py, common.py)
# ---------------------------------------------------------------------------

@_export
def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    return _op("fold", x, output_sizes=output_sizes,
               kernel_sizes=kernel_sizes, strides=strides, paddings=paddings,
               dilations=dilations)


@_export
def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    return _op("pixel_unshuffle", x, downscale_factor=downscale_factor,
               data_format=data_format)


@_export
def channel_shuffle(x, groups, data_format="NCHW", name=None):
    return _op("channel_shuffle", x, groups=groups, data_format=data_format)


@_export
def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    return _op("temporal_shift", x, seg_num=seg_num, shift_ratio=shift_ratio,
               data_format=data_format)


@_export
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    return _op("grid_sample", x, grid, mode=mode, padding_mode=padding_mode,
               align_corners=align_corners)


@_export
def affine_grid(theta, out_shape, align_corners=True, name=None):
    if isinstance(out_shape, Tensor):
        out_shape = [int(v) for v in np.asarray(out_shape._data)]
    return _op("affine_grid", theta, out_shape=tuple(int(v)
               for v in out_shape), align_corners=align_corners)


@_export
def zeropad2d(x, padding, data_format="NCHW", name=None):
    if isinstance(padding, int):
        padding = [padding] * 4
    return pad(x, padding, mode="constant", value=0.0,
               data_format=data_format)


@_export
def bilinear(x1, x2, weight, bias=None, name=None):
    return _op("bilinear", x1, x2, weight, bias)


@_export
def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    return _op("diag_embed", x, offset=offset, dim1=dim1, dim2=dim2)


@_export
def gather_tree(ids, parents):
    return _op("gather_tree", ids, parents)


# ---------------------------------------------------------------------------
# extra losses (reference: loss.py, distance.py)
# ---------------------------------------------------------------------------

def _reduce(loss, reduction):
    if reduction == "mean":
        return _op("mean", loss)
    if reduction == "sum":
        return _op("sum", loss)
    return loss


@_export
def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Reference semantics (nn/functional/loss.py ctc_loss over warpctc):
    per-sample NLL; 'mean' divides by label length then averages;
    norm_by_times divides each sample's loss by its input length first."""
    loss = _op("ctc_loss", log_probs, labels, input_lengths, label_lengths,
               blank=blank)
    if norm_by_times:
        il = _op("cast", input_lengths, dtype="float32")
        loss = _op("divide", loss,
                   _op("maximum", il, _op("full_like", il, fill_value=1.0)))
    if reduction == "mean":
        ll = _op("cast", label_lengths, dtype="float32")
        return _op("mean", _op("divide", loss,
                               _op("maximum", ll, _op("full_like", ll, fill_value=1.0))))
    return _reduce(loss, reduction)


@_export
def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    return _op("hsigmoid_loss", input, label, weight, bias, path_table,
               path_code, num_classes=num_classes)


@_export
def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    out = _op("margin_cross_entropy", logits, label, margin1=margin1,
              margin2=margin2, margin3=margin3, scale=scale,
              return_softmax=return_softmax)
    if return_softmax:
        loss, softmax_out = out
        return _reduce(loss, reduction), softmax_out
    return _reduce(out, reduction)


@_export
def class_center_sample(label, num_classes, num_samples, group=None):
    return _op("class_center_sample", label, num_classes=num_classes,
               num_samples=num_samples)


@_export
def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    d = _op("subtract", x, y)
    d = _op("add", d, _op("full_like", d, fill_value=float(epsilon)))
    return _op("p_norm", d, porder=float(p), axis=-1, keepdim=keepdim)


@_export
def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    cos = _op("cosine_similarity", input1, input2, axis=-1, eps=1e-8)
    one = _op("full_like", cos, fill_value=1.0)
    zero = _op("full_like", cos, fill_value=0.0)
    pos = _op("subtract", one, cos)
    neg = _op("maximum", _op("subtract", cos,
                             _op("scale", one, scale=float(margin))), zero)
    lab = _op("cast", label, dtype=cos.dtype)
    is_pos = _op("cast", _op("equal", lab, one), cos.dtype)
    is_neg = _op("cast", _op("equal", lab, _op("scale", one, scale=-1.0)),
                 cos.dtype)
    loss = _op("add", _op("multiply", is_pos, pos),
               _op("multiply", is_neg, neg))
    return _reduce(loss, reduction)


@_export
def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    dp = pairwise_distance(input, positive, p, epsilon)
    dn = pairwise_distance(input, negative, p, epsilon)
    if swap:
        dn2 = pairwise_distance(positive, negative, p, epsilon)
        dn = _op("minimum", dn, dn2)
    marg = _op("full_like", dp, fill_value=float(margin))
    loss = _op("maximum", _op("add", _op("subtract", dp, dn), marg),
               _op("full_like", dp, fill_value=0.0))
    return _reduce(loss, reduction)


@_export
def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    dist = distance_function if distance_function is not None else \
        (lambda a, b: pairwise_distance(a, b, 2.0))
    dp = dist(input, positive)
    dn = dist(input, negative)
    if swap:
        dn2 = dist(positive, negative)
        dn = _op("minimum", dn, dn2)
    marg = _op("full_like", dp, fill_value=float(margin))
    loss = _op("maximum", _op("add", _op("subtract", dp, dn), marg),
               _op("full_like", dp, fill_value=0.0))
    return _reduce(loss, reduction)


@_export
def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    lab = _op("cast", label, dtype=input.dtype
              if hasattr(input, "dtype") else "float32")
    pos = _op("log_sigmoid", input)
    neg = _op("log_sigmoid", _op("scale", input, scale=-1.0))
    one = _op("full_like", lab, fill_value=1.0)
    per = _op("add", _op("multiply", lab, pos),
              _op("multiply", _op("subtract", one, lab), neg))
    if weight is not None:
        per = _op("multiply", per, weight)
    loss = _op("scale", _op("mean", per, axis=-1), scale=-1.0)
    return _reduce(loss, reduction)


@_export
def dice_loss(input, label, epsilon=1e-5, name=None):
    """input: [N, ..., C] probabilities, label: [N, ..., 1] int (reference:
    nn/functional/loss.py dice_loss)."""
    nc = input.shape[-1]
    lab = _op("squeeze", label, axis=-1)
    oh = _op("one_hot", lab, num_classes=nc)
    ohf = _op("cast", oh, dtype=input.dtype)
    axes = tuple(range(1, len(input.shape)))
    inter = _op("sum", _op("multiply", input, ohf), axis=axes)
    union = _op("add", _op("sum", input, axis=axes),
                _op("sum", ohf, axis=axes))
    num = _op("scale", inter, scale=2.0)
    eps = _op("full_like", union, fill_value=float(epsilon))
    dice = _op("divide", num, _op("add", union, eps))
    one = _op("full_like", dice, fill_value=1.0)
    return _op("mean", _op("subtract", one, dice))


@_export
def log_loss(input, label, epsilon=1e-4, name=None):
    eps = _op("full_like", input, fill_value=float(epsilon))
    one = _op("full_like", input, fill_value=1.0)
    t1 = _op("multiply", label, _op("log", _op("add", input, eps)))
    t2 = _op("multiply", _op("subtract", one, label),
             _op("log", _op("add", _op("subtract", one, input), eps)))
    return _op("scale", _op("add", t1, t2), scale=-1.0)


@_export
def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """Reference: nn/functional/loss.py npair_loss — softmax CE over
    anchor·positiveᵀ similarities with same-label targets + L2 term."""
    sim = _op("matmul", anchor, positive, transpose_y=True)
    lab = _op("cast", labels, dtype=sim.dtype)
    n = lab.shape[0]
    li = _op("reshape", lab, shape=(n, 1))
    eq = _op("cast", _op("equal", li, _op("reshape", lab, shape=(1, n))),
             sim.dtype)
    row = _op("sum", eq, axis=1, keepdim=True)
    tgt = _op("divide", eq, row)
    ce = _op("softmax_with_cross_entropy", sim, tgt, soft_label=True)
    l2 = _op("scale", _op("add", _op("sum", _op("multiply", anchor, anchor)),
                          _op("sum", _op("multiply", positive, positive))),
             scale=float(l2_reg) * 0.25 / int(n))
    return _op("add", _op("mean", ce), l2)


# ---------------------------------------------------------------------------
# in-place aliases. Tensors here are facades over immutable jax arrays; the
# in-place API rebinds the underlying buffer, matching the reference's
# observable semantics (autograd through in-place ops is likewise undefined
# in the reference's _ variants).
# ---------------------------------------------------------------------------

def _inplace(fn, name):
    def f(x, *args, **kwargs):
        out = fn(x, *args, **kwargs)
        if isinstance(x, Tensor) and isinstance(out, Tensor):
            x._data = out._data
            return x
        return out
    f.__name__ = name
    return _export(f)


relu_ = _inplace(relu, "relu_")
elu_ = _inplace(elu, "elu_")
tanh_ = _inplace(tanh, "tanh_")
softmax_ = _inplace(softmax, "softmax_")
