"""Beam-search decoding.

Analog of the reference's ``python/paddle/nn/decode.py`` (BeamSearchDecoder +
dynamic_decode over an RNN cell). TPU-native shape: the decode loop is a
fixed-length ``lax.scan`` with a finished mask (static shapes, compiles once)
instead of the reference's data-dependent while loop; the ancestry walk at the
end is the ``gather_tree`` op.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.dispatch import call_op as _op
from ..framework.tensor import Tensor
from . import functional as F

__all__ = ["BeamSearchDecoder", "dynamic_decode"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class BeamSearchDecoder:
    """Wraps an RNNCellBase-style cell into a beam-search decoder.

    cell(inputs, states) -> (outputs, new_states); an output layer maps cell
    outputs to vocab logits. Mirrors the reference API:
    ``BeamSearchDecoder(cell, start_token, end_token, beam_size, embedding_fn,
    output_fn)``.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers -----------------------------------------------------------

    def _merge(self, x):
        # [B, K, ...] -> [B*K, ...]
        return x.reshape((-1,) + x.shape[2:])

    def _split(self, x, batch):
        return x.reshape((batch, self.beam_size) + x.shape[1:])

    def initialize(self, initial_states, batch_size):
        k = self.beam_size
        tok = jnp.full((batch_size, k), self.start_token, jnp.int32)
        # log-prob carry: beam 0 live, others -inf so step 1 fans out
        lp = jnp.tile(
            jnp.array([[0.0] + [-1e9] * (k - 1)], jnp.float32),
            (batch_size, 1))
        fin = jnp.zeros((batch_size, k), bool)
        tiled = jax.tree_util.tree_map(
            lambda s: jnp.repeat(_arr(s), k, axis=0), initial_states)
        return tok, lp, fin, tiled

    def step(self, tokens, log_probs, finished, states, batch):
        k = self.beam_size
        inp = tokens.reshape(-1)
        if self.embedding_fn is not None:
            emb = self.embedding_fn(Tensor(inp))
            emb = _arr(emb)
        else:
            emb = inp
        out, new_states = self.cell(Tensor(emb), jax.tree_util.tree_map(
            Tensor, states))
        out = _arr(out)
        new_states = jax.tree_util.tree_map(_arr, new_states)
        if self.output_fn is not None:
            logits = _arr(self.output_fn(Tensor(out)))
        else:
            logits = out
        vocab = logits.shape[-1]
        step_lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        step_lp = step_lp.reshape(batch, k, vocab)
        # finished beams only extend with end_token at zero cost
        frozen = jnp.full((vocab,), -1e9, jnp.float32).at[
            self.end_token].set(0.0)
        step_lp = jnp.where(finished[..., None], frozen, step_lp)
        total = log_probs[..., None] + step_lp          # [B, K, V]
        flat = total.reshape(batch, k * vocab)
        top_lp, top_idx = jax.lax.top_k(flat, k)
        parent = (top_idx // vocab).astype(jnp.int32)   # [B, K]
        token = (top_idx % vocab).astype(jnp.int32)
        new_finished = jnp.take_along_axis(finished, parent, axis=1) | (
            token == self.end_token)
        # reorder states by parent beam
        gidx = (jnp.arange(batch)[:, None] * k + parent).reshape(-1)
        new_states = jax.tree_util.tree_map(
            lambda s: jnp.take(s, gidx, axis=0), new_states)
        return token, top_lp, new_finished, new_states, parent


def dynamic_decode(decoder, inits=None, max_step_num=32, batch_size=None,
                   output_time_major=False, **kwargs):
    """Run the decoder up to ``max_step_num`` steps (fixed-length scan).

    Returns (ids [B, K, T] int32, final log-probs [B, K]) after the
    gather_tree ancestry resolution — the reference returns the same pair.
    """
    if batch_size is None:
        leaf = jax.tree_util.tree_leaves(inits)[0]
        batch_size = _arr(leaf).shape[0]
    k = decoder.beam_size
    tok, lp, fin, states = decoder.initialize(inits, batch_size)

    tokens_acc = []
    parents_acc = []
    # python loop over static max_step_num: each step's cell call goes
    # through the dispatch layer (jit-cached); the whole decode can itself
    # sit under jit where it becomes one traced loop.
    for _ in range(int(max_step_num)):
        tok, lp, fin, states, parent = decoder.step(
            tok, lp, fin, states, batch_size)
        tokens_acc.append(tok)
        parents_acc.append(parent)
        # early exit only when running eagerly; under jit `fin` is a tracer
        # and the loop simply runs the full static length
        if not isinstance(fin, jax.core.Tracer) and bool(jnp.all(fin)):
            break
    ids = jnp.stack(tokens_acc)        # [T, B, K]
    parents = jnp.stack(parents_acc)   # [T, B, K]
    resolved = _op("gather_tree", Tensor(ids), Tensor(parents))
    out = _arr(resolved)
    if not output_time_major:
        out = jnp.transpose(out, (1, 2, 0))
    return Tensor(out), Tensor(lp)
