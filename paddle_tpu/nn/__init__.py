"""``paddle.nn`` — neural network layers.

Analog of the reference's ``python/paddle/nn/__init__.py``: re-exports the
Layer base, containers, and all layer families.
"""
from __future__ import annotations

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from . import quant  # noqa: F401
from .clip import (  # noqa: F401
    ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
)
from .layer.layers import Layer, ParamAttr  # noqa: F401
from .layer.container import (  # noqa: F401
    LayerDict, LayerList, ParameterList, Sequential,
)
from .layer.common import (  # noqa: F401
    AlphaDropout, Bilinear, ChannelShuffle, CosineSimilarity, Dropout,
    Dropout2D, Dropout3D, Embedding, Flatten, Fold, Identity, Linear, Pad1D,
    Pad2D, Pad3D, PairwiseDistance, PixelShuffle, PixelUnshuffle, Unfold,
    Upsample, UpsamplingBilinear2D, UpsamplingNearest2D, ZeroPad2D,
)
from .layer.conv import (  # noqa: F401
    Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D,
    Conv3DTranspose,
)
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm,
    InstanceNorm1D, InstanceNorm2D, InstanceNorm3D, LayerNorm,
    LocalResponseNorm, RMSNorm, SpectralNorm, SyncBatchNorm,
)
from .layer.pooling import (  # noqa: F401
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D, AvgPool1D,
    AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D, MaxPool3D, MaxUnPool1D,
    MaxUnPool2D, MaxUnPool3D,
)
from .layer.activation import (  # noqa: F401
    CELU, ELU, GELU, GLU, Hardshrink, Hardsigmoid, Hardswish, Hardtanh,
    LeakyReLU, LogSigmoid, LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6,
    RReLU, SELU, Sigmoid, Silu, Softmax, Softmax2D, Softplus, Softshrink,
    Softsign, Swish, Tanh, Tanhshrink, ThresholdedReLU,
)
from .layer.loss import (  # noqa: F401
    BCELoss, BCEWithLogitsLoss, CosineEmbeddingLoss, CrossEntropyLoss,
    CTCLoss, HingeEmbeddingLoss, HSigmoidLoss, HuberLoss, KLDivLoss, L1Loss,
    MarginRankingLoss, MSELoss, MultiLabelSoftMarginLoss, NLLLoss,
    SigmoidFocalLoss, SmoothL1Loss, TripletMarginLoss,
    TripletMarginWithDistanceLoss,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerDecoder,
    TransformerDecoderLayer, TransformerEncoder, TransformerEncoderLayer,
)
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
from .layer.rnn import (  # noqa: F401
    BiRNN, GRU, GRUCell, LSTM, LSTMCell, RNN, RNNCellBase, SimpleRNN,
    SimpleRNNCell,
)
