"""Gradient clipping.

Analog of the reference's ``python/paddle/nn/clip.py`` (ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm). Clips operate on (param, grad) lists
inside ``Optimizer.step``; the global-norm reduction is a pure jax reduction,
so under a sharded train step XLA turns it into the cross-chip psum the
reference implements by hand in HybridParallelOptimizer.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["ClipGradBase", "ClipGradByValue", "ClipGradByNorm",
           "ClipGradByGlobalNorm", "clip_by_norm", "clip_by_global_norm"]


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        return [(p, jnp.clip(g, self.min, self.max)) for p, g in
                params_grads]


class ClipGradByNorm(ClipGradBase):
    """Per-tensor L2-norm clip."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                1.0)
            out.append((p, (g * scale).astype(g.dtype)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Joint L2-norm clip over all grads (the default for LLM training)."""

    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        return self.clip_with_norm(params_grads)[0]

    def clip_with_norm(self, params_grads):
        """Clip AND return the pre-clip global norm: ``(out_pairs,
        global_norm)``. The numerics audit of the donated train step
        (profiler/numerics.py) reads the norm from here instead of
        reducing the gradient tree a second time — the clip path
        already paid for it."""
        if not params_grads:
            return params_grads, jnp.zeros((), jnp.float32)
        sq = [jnp.sum(jnp.square(g.astype(jnp.float32)))
              for p, g in params_grads
              if not getattr(p, "need_clip", True) is False]
        global_norm = jnp.sqrt(jnp.asarray(sq).sum())
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if getattr(p, "need_clip", True) is False:
                out.append((p, g))
            else:
                out.append((p, (g * scale).astype(g.dtype)))
        return out, global_norm


def clip_by_norm(x, max_norm):
    from ..framework.dispatch import call_op
    import jax.numpy as jnp  # noqa: F811
    norm = jnp.sqrt(jnp.sum(jnp.square(x._data)))
    scale = jnp.minimum(max_norm / jnp.maximum(norm, 1e-12), 1.0)
    from ..framework.tensor import Tensor
    return call_op("scale", x, scale=scale, bias=0.0)


def clip_by_global_norm(t_list, clip_norm):
    clip = ClipGradByGlobalNorm(clip_norm)
    pairs = [(t, t._data) for t in t_list]
    from ..framework.tensor import Tensor
    return [Tensor(g) for _, g in clip(pairs)]
