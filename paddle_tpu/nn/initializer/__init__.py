"""Parameter initializers.

Analog of the reference's ``python/paddle/fluid/initializer.py`` (Constant,
Uniform, Normal, TruncatedNormal, Xavier, MSRA/Kaiming, Bilinear, Assign) and
``python/paddle/nn/initializer/``. TPU-native difference: an initializer is a
pure function ``(shape, dtype) -> jax array`` drawing from the functional PRNG
(framework/random.py) instead of appending fill ops to a startup program.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import random as _random
from ...framework.dtypes import convert_dtype

__all__ = [
    "Bilinear", "set_global_initializer",
    "Initializer", "Constant", "Uniform", "Normal", "TruncatedNormal",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain",
]


def calculate_gain(nonlinearity, param=None):
    recommended = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0, "conv2d": 1.0, "conv3d": 1.0,
        "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0,
        "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None
                                             else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity not in recommended:
        raise ValueError(f"unsupported nonlinearity: {nonlinearity}")
    return recommended[nonlinearity]


def _fan_in_out(shape):
    """Fan computation matching the reference's Xavier/MSRA initializers:
    for conv weights (OIHW), receptive field multiplies the channel fans."""
    shape = tuple(int(s) for s in shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle Linear weight is [in_features, out_features]
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=convert_dtype(dtype))


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(
            _random.next_key(), shape, dtype=jnp.float32,
            minval=self.low, maxval=self.high).astype(convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        a = jax.random.normal(_random.next_key(), shape, dtype=jnp.float32)
        return (a * self.std + self.mean).astype(convert_dtype(dtype))


class TruncatedNormal(Initializer):
    """Normal truncated at 2 std devs (matches the reference's
    truncated_gaussian_random op)."""

    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        a = jax.random.truncated_normal(
            _random.next_key(), -2.0, 2.0, shape, dtype=jnp.float32)
        return (a * self.std + self.mean).astype(convert_dtype(dtype))


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self._fan_in, self._fan_out, self._gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        limit = self._gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(
            _random.next_key(), shape, dtype=jnp.float32,
            minval=-limit, maxval=limit).astype(convert_dtype(dtype))


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self._fan_in, self._fan_out, self._gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        std = self._gain * math.sqrt(2.0 / (fi + fo))
        a = jax.random.normal(_random.next_key(), shape, dtype=jnp.float32)
        return (a * std).astype(convert_dtype(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu",
                 name=None):
        self._fan_in = fan_in
        self._gain = calculate_gain(nonlinearity, negative_slope)

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        limit = self._gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(
            _random.next_key(), shape, dtype=jnp.float32,
            minval=-limit, maxval=limit).astype(convert_dtype(dtype))


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu",
                 name=None):
        self._fan_in = fan_in
        self._gain = calculate_gain(nonlinearity, negative_slope)

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        std = self._gain / math.sqrt(fi)
        a = jax.random.normal(_random.next_key(), shape, dtype=jnp.float32)
        return (a * std).astype(convert_dtype(dtype))


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype):
        arr = jnp.asarray(np.asarray(self.value),
                          dtype=convert_dtype(dtype))
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(shape)
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype):
        shape = tuple(int(s) for s in shape)
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = (max(rows, cols), min(rows, cols))
        a = jax.random.normal(_random.next_key(), flat, dtype=jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diag(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(
            convert_dtype(dtype))


class Dirac(Initializer):
    """Identity-preserving conv init (reference nn/initializer/dirac.py)."""

    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype):
        shape = tuple(int(s) for s in shape)
        out_c, in_c = shape[0], shape[1]
        w = np.zeros(shape, dtype=np.float32)
        centre = tuple(s // 2 for s in shape[2:])
        per_group = out_c // self.groups
        for g in range(self.groups):
            for i in range(min(per_group, in_c)):
                w[(g * per_group + i, i) + centre] = 1.0
        return jnp.asarray(w, dtype=convert_dtype(dtype))


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed convs (reference:
    nn/initializer/Bilinear — fluid/initializer.py BilinearInitializer)."""

    def __call__(self, shape, dtype):
        shape = tuple(int(s) for s in shape)
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D weight")
        kh, kw = shape[2], shape[3]
        f_h = (kh + 1) // 2
        f_w = (kw + 1) // 2
        c_h = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        c_w = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        og = np.ogrid[:kh, :kw]
        filt = (1 - abs(og[0] / f_h - c_h)) * (1 - abs(og[1] / f_w - c_w))
        w = np.zeros(shape, np.float32)
        for i in range(shape[0]):
            for j in range(shape[1]):
                w[i, j] = filt
        return jnp.asarray(w, dtype=convert_dtype(dtype))


_GLOBAL_INIT = {"weight": None, "bias": None}


def set_global_initializer(weight_init, bias_init=None):
    """Reference: nn/initializer/set_global_initializer — default
    initializers used by create_parameter when neither the ParamAttr nor
    the layer specifies one. Pass (None, None) to reset."""
    _GLOBAL_INIT["weight"] = weight_init
    _GLOBAL_INIT["bias"] = bias_init


def _global_initializer(is_bias: bool):
    return _GLOBAL_INIT["bias" if is_bias else "weight"]
