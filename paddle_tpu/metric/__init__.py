"""``paddle.metric`` — training metrics.

Analog of the reference's ``python/paddle/metric/metrics.py`` (Metric base,
Accuracy, Precision, Recall, Auc). Metrics accumulate on host over numpy
results of the jitted step.
"""
from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional pre-processing hook run on step outputs (can stay inside
        the jitted region); defaults to identity passthrough."""
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = _np(label)
        idx = np.argsort(-pred, axis=-1)[..., : self.maxk]
        if label.ndim == pred.ndim:  # one-hot / [N,1] sparse
            if label.shape[-1] == 1:
                label = label[..., 0]
            else:
                label = label.argmax(-1)
        return (idx == label[..., None]).astype(np.float32)

    def update(self, correct, *args):
        correct = _np(correct)
        accs = []
        for k in self.topk:
            num = correct[..., :k].sum()
            accs.append(num / max(1, correct.shape[0]))
            self.total[self.topk.index(k)] += num
        self.count += correct.shape[0]
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = 0

    def accumulate(self):
        res = [t / max(1, self.count) for t in self.total]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        labels = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        labels = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Histogram-bucketed ROC AUC (reference metrics.py Auc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        if preds.ndim == 2:  # [N,2] class probs -> positive prob
            preds = preds[:, -1]
        preds = preds.reshape(-1)
        buckets = np.clip(
            (preds * self.num_thresholds).astype(np.int64), 0,
            self.num_thresholds)
        for b, l in zip(buckets, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        # walk thresholds high->low accumulating trapezoids
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * self._stat_neg[i] / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return auc / tot_pos / tot_neg

    def name(self):
        return self._name


def accuracy(input, label, k=1):
    """Functional top-k accuracy."""
    m = Accuracy(topk=(k,))
    correct = m.compute(input, label)
    m.update(correct)
    return m.accumulate()
