"""The built-in analysis passes.

Each is a function ``(ctx: AnalysisContext) -> list[Finding]`` registered
under its pass id (≙ REGISTER_PASS in the reference's
paddle/fluid/framework/ir). A pass that needs a context facility the
driver could not produce (no jaxpr because tracing failed, no grad info)
returns [] — the other passes still run.

Severity policy (what "clean bill" means for the zoo train steps):

* **error** — the program is wrong or will corrupt state: host
  concretization inside a traced fn, a donated buffer with no matching
  output (the caller's rebind target does not exist — every later read
  hits "Array has been deleted"), a trainable parameter with a
  structurally-zero gradient (the optimizer still applies weight decay /
  moment updates to it — the PR-2 frozen-param bug class).
* **warning** — probably costing performance or correctness headroom:
  host callbacks in the hot loop, f64 leaks, repeated shape/dtype-caused
  retraces, a flapping frozen set.
* **info** — worth knowing, expected in some designs: bf16→f32 upcasts
  inside an autocast region, low-count retrace summaries.
"""
from __future__ import annotations

from typing import List

import numpy as np

from .core import (AnalysisContext, Finding, eqn_source, is_structural_zero,
                   iter_eqns, register_pass)

__all__ = ["host_sync_pass", "donation_safety_pass", "dead_grad_pass",
           "dtype_hygiene_pass", "recompile_churn_pass",
           "collective_pairing_pass", "static_memory_pass",
           "donation_miss_pass", "sharding_consistency_pass"]


# ---------------------------------------------------------------------------
# 1. host-sync
# ---------------------------------------------------------------------------

_CALLBACK_PRIMS = {
    "pure_callback": "jax.pure_callback",
    "io_callback": "jax.experimental.io_callback",
    "debug_callback": "jax.debug.callback",
    "callback": "host callback",
}


@register_pass("host-sync")
def host_sync_pass(ctx: AnalysisContext) -> List[Finding]:
    """Host round-trips inside the traced computation.

    Two shapes: (a) the trace itself died on a concretization —
    ``.numpy()`` / ``float()`` / ``bool()`` / ``np.asarray`` on a traced
    value — which the driver caught and source-located (the raw
    ConcretizationTypeError fires deep inside jax where the call site is
    invisible); (b) callback-shaped eqns (pure_callback / io_callback),
    which run but serialize device against host every step."""
    out: List[Finding] = []
    if ctx.trace_error is not None:
        kind = type(ctx.trace_error).__name__
        out.append(Finding(
            pass_id="host-sync", severity="error",
            message=(f"host concretization inside the traced function "
                     f"({kind}): a .numpy()/float()/bool()/np.asarray on "
                     f"a traced value forces a device sync and breaks "
                     f"under jit"),
            source=ctx.trace_error_source,
            fix_hint=("keep host reads out of the step: return the value "
                      "and fetch it outside, or use a windowed flush "
                      "(Model.fit syncs once per log_freq steps)")))
        return out
    if ctx.closed_jaxpr is None:
        return out
    for eqn in iter_eqns(ctx.closed_jaxpr):
        prim = eqn.primitive.name
        if prim in _CALLBACK_PRIMS:
            out.append(Finding(
                pass_id="host-sync", severity="warning",
                message=(f"{_CALLBACK_PRIMS[prim]} inside the traced "
                         f"computation: one device->host->device round "
                         f"trip per execution"),
                source=eqn_source(eqn), primitive=prim,
                fix_hint=("intended for host-only kernels (e.g. "
                          "nonsymmetric eig, MIGRATION.md); keep it out "
                          "of per-step hot loops or precompute on host")))
    return out


# ---------------------------------------------------------------------------
# 2. donation-safety
# ---------------------------------------------------------------------------

def _aval_key(v):
    aval = v.aval
    return (tuple(aval.shape), str(aval.dtype))


@register_pass("donation-safety")
def donation_safety_pass(ctx: AnalysisContext) -> List[Finding]:
    """Donated inputs whose buffers are structurally unsafe.

    A donated input's buffer is deleted at dispatch; the caller's only
    valid move is rebinding to a same-shape/dtype output (the PR-2
    donated train step contract). Structurally checkable: (a) a donated
    invar with NO matching output aval — the rebind target does not
    exist, so the state the caller holds after the call is a deleted
    handle (error); (b) one donated invar feeding MORE outputs than
    exist buffers to alias (double-alias, error). The old boolean
    dead-donation warning moved to the byte-aware ``donation-miss``
    pass (ISSUE 18), which prices every donation decision."""
    out: List[Finding] = []
    closed, mask = ctx.closed_jaxpr, ctx.donated_invars
    if closed is None or not mask or not any(mask):
        return out
    jaxpr = closed.jaxpr
    donated = [v for v, d in zip(jaxpr.invars, mask) if d]

    # multiset of output avals available for aliasing
    from collections import Counter
    out_avals = Counter(_aval_key(v) for v in jaxpr.outvars
                        if not hasattr(v, "val"))
    outvar_counts = Counter(id(v) for v in jaxpr.outvars)

    for i, v in enumerate(donated):
        key = _aval_key(v)
        if outvar_counts.get(id(v), 0) > 1:
            out.append(Finding(
                pass_id="donation-safety", severity="error",
                message=(f"donated input #{i} ({key[1]}{list(key[0])}) is "
                         f"returned as more than one output — two "
                         f"outputs cannot alias one donated buffer"),
                fix_hint="return a copy for one of the aliases"))
            continue
        if out_avals.get(key, 0) > 0:
            out_avals[key] -= 1
            continue
        out.append(Finding(
            pass_id="donation-safety", severity="error",
            message=(f"donated input #{i} ({key[1]}{list(key[0])}) has no "
                     f"matching output: its buffer is deleted at "
                     f"dispatch but nothing replaces it — any state the "
                     f"caller rebinds is a deleted handle"),
            fix_hint=("return the updated value for every donated arg "
                      "(params/opt_state/buffers in a train step) or "
                      "drop it from donate_argnums")))
    return out


# ---------------------------------------------------------------------------
# 3. dead/frozen-grad
# ---------------------------------------------------------------------------

@register_pass("dead-grad")
def dead_grad_pass(ctx: AnalysisContext) -> List[Finding]:
    """Parameters whose cotangent is structurally zero in the grad jaxpr.

    jax AD materializes a symbolic-zero cotangent as
    ``broadcast_in_dim [0.0]`` — no dependence on any input. A trainable
    parameter with such a gradient is the exact latent bug PR 2 found by
    hand: the optimizer still applies weight decay and moment updates to
    it, silently training (decaying) a parameter the loss never sees.
    Requires grad info from the driver (``analyze_model`` supplies it);
    returns [] otherwise."""
    out: List[Finding] = []
    info = ctx.grad
    if not info or info.get("jaxpr") is None:
        return out
    closed = info["jaxpr"]
    names = info.get("names") or []
    trainable = info.get("trainable")
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    for i, v in enumerate(jaxpr.outvars):
        if not (hasattr(v, "val") or is_structural_zero(jaxpr, v)):
            continue
        if hasattr(v, "val") and np.any(np.asarray(v.val)):
            continue  # constant but nonzero: not a dead grad
        pname = names[i] if i < len(names) else f"output[{i}]"
        in_train = trainable is None or pname in trainable
        out.append(Finding(
            pass_id="dead-grad",
            severity="error" if in_train else "info",
            message=(f"parameter '{pname}' receives a structurally-zero "
                     f"gradient" +
                     (" but is in the trainable set — the optimizer "
                      "will still weight-decay/update it" if in_train
                      else " (frozen, as declared)")),
            fix_hint=("if freezing is intended, set stop_gradient=True "
                      "so the step bakes it out of the trainable split; "
                      "if not, the loss never reads this parameter — "
                      "check the forward wiring")))
    return out


# ---------------------------------------------------------------------------
# 4. dtype-hygiene
# ---------------------------------------------------------------------------

_MAX_SITES = 3  # provenance examples per finding class before aggregating


@register_pass("dtype-hygiene")
def dtype_hygiene_pass(ctx: AnalysisContext) -> List[Finding]:
    """f64 leaks and silent bf16->f32 upcasts.

    f64: TPUs emulate double precision at a large slowdown, and with
    jax's default x64-off config a float64 numpy input is silently
    downcast — both directions are a data-pipeline leak
    (``np.random.randn`` is float64!). bf16 upcasts: inside a program
    that demonstrably runs a bf16 region (bf16 inputs or f32->bf16
    downcasts present), every bf16->f32 convert re-doubles the memory
    the autocast saved — expected for loss accumulation, a bug when it
    hits activations."""
    out: List[Finding] = []
    for a in _np_leaves(ctx.args):
        if a.dtype in (np.float64, np.complex128):
            out.append(Finding(
                pass_id="dtype-hygiene", severity="warning",
                message=(f"float64 host input (shape "
                         f"{list(a.shape)}): silently downcast to f32 "
                         f"under jax's default config, or computed at "
                         f"~10x cost on TPU with x64 on"),
                fix_hint="cast the pipeline to float32 at the source "
                         "(np.float32 / .astype('float32'))"))
            break  # one finding per run is enough signal
    closed = ctx.closed_jaxpr
    if closed is None:
        return out

    def _dt(v) -> str:
        aval = getattr(v, "aval", None)
        return str(getattr(aval, "dtype", ""))

    f64_sites, upcast_sites = [], []
    has_bf16_region = any(_dt(v) == "bfloat16"
                          for v in closed.jaxpr.invars)
    for eqn in iter_eqns(closed):
        for v in eqn.outvars:
            if _dt(v) in ("float64", "complex128"):
                f64_sites.append(eqn_source(eqn))
                break
        if eqn.primitive.name == "convert_element_type":
            src_dt = _dt(eqn.invars[0])
            dst_dt = str(eqn.params.get("new_dtype", ""))
            if src_dt == "float32" and dst_dt == "bfloat16":
                has_bf16_region = True
            if src_dt == "bfloat16" and dst_dt == "float32":
                upcast_sites.append(eqn_source(eqn))
    if f64_sites:
        sites = ", ".join(s for s in f64_sites[:_MAX_SITES] if s)
        out.append(Finding(
            pass_id="dtype-hygiene", severity="warning",
            message=(f"{len(f64_sites)} eqn(s) produce float64/"
                     f"complex128 values (first at: {sites or 'n/a'})"),
            source=f64_sites[0],
            fix_hint="stay fp32/bf16 on TPU; fp64 is emulated"))
    if upcast_sites and has_bf16_region:
        sites = ", ".join(s for s in upcast_sites[:_MAX_SITES] if s)
        out.append(Finding(
            pass_id="dtype-hygiene", severity="info",
            message=(f"{len(upcast_sites)} bf16->f32 upcast(s) inside a "
                     f"bf16/autocast region (first at: {sites or 'n/a'})"),
            source=upcast_sites[0],
            fix_hint=("expected for loss/reduction accumulation; if an "
                      "activation path upcasts, check the amp "
                      "allow/deny lists")))
    return out


def _np_leaves(args):
    import jax
    for leaf in jax.tree_util.tree_leaves(
            args, is_leaf=lambda x: isinstance(x, np.ndarray)):
        if isinstance(leaf, np.ndarray):
            yield leaf


# ---------------------------------------------------------------------------
# 5. collective-pairing
# ---------------------------------------------------------------------------

def _axis_key(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v,)


@register_pass("collective-pairing")
def collective_pairing_pass(ctx: AnalysisContext) -> List[Finding]:
    """Reduce-scatter / all-gather pairing over the traced program.

    The ZeRO-sharded weight update's contract is a closed loop:
    gradients reduce-scatter over a mesh axis into 1/dp stripes, and
    the updated stripes all-gather back over the SAME axis and
    dimension with the SAME tiling. A reduce-scatter whose (axis,
    dimension, tiled) triple has no matching all-gather leaves the
    caller holding a shard it will treat as the full value — the
    sharded analog of a donated invar with no rebind target — and a
    gather on a DIFFERENT axis/dimension re-assembles the stripes in
    the wrong order (silently permuted parameters). psum-only programs
    (plain data-parallel grad sync) never trip this: the pass only
    speaks when reduce_scatter eqns exist."""
    out: List[Finding] = []
    if ctx.closed_jaxpr is None:
        return out
    # program order matters: an all-gather can only CLOSE a
    # reduce-scatter that precedes it (iter_eqns yields eqns in
    # program order) — an unrelated gather at the top of the step must
    # not be consumed as the match for a later unclosed scatter
    rs, ag = [], []
    for pos, eqn in enumerate(iter_eqns(ctx.closed_jaxpr)):
        name = eqn.primitive.name
        if name == "reduce_scatter":
            rs.append((pos, eqn))
        elif name == "all_gather":
            ag.append((pos, eqn))
    if not rs:
        return out

    def _ag_key(e):
        return (_axis_key(e.params.get("axis_name")),
                int(e.params.get("all_gather_dimension", 0)),
                bool(e.params.get("tiled", False)))

    unconsumed = list(ag)  # (pos, eqn), program order
    for rs_pos, e in rs:
        key = (_axis_key(e.params.get("axis_name")),
               int(e.params.get("scatter_dimension", 0)),
               bool(e.params.get("tiled", False)))
        match = next((i for i, (p, g) in enumerate(unconsumed)
                      if p > rs_pos and _ag_key(g) == key), None)
        if match is not None:
            unconsumed.pop(match)
            continue
        axis, dim, tiled = key
        same_axis = [
            _ag_key(g) for p, g in unconsumed
            if p > rs_pos and _ag_key(g)[0] == axis]
        if same_axis:
            have = ", ".join(f"dim={k[1]} tiled={k[2]}"
                             for k in same_axis)
            msg = (f"reduce-scatter over axis {axis} (dim={dim}, "
                   f"tiled={tiled}) does not match its closing "
                   f"all-gather ({have}): the stripes re-assemble "
                   f"permuted")
        else:
            msg = (f"reduce-scatter over axis {axis} (dim={dim}, "
                   f"tiled={tiled}) has no closing all-gather on that "
                   f"axis: downstream code holds a 1/N shard where it "
                   f"expects the full value")
        out.append(Finding(
            pass_id="collective-pairing", severity="error",
            message=msg, source=eqn_source(e),
            primitive="reduce_scatter",
            fix_hint=("close the sharded region with all_gather_in_axis "
                      "over the same axis/dimension/tiling, or keep the "
                      "value sharded on purpose via an explicit "
                      "out_spec (then psum_scatter is not the right "
                      "primitive to lint — wrap it outside the "
                      "analyzed step)")))
    return out


# ---------------------------------------------------------------------------
# 6. recompile-churn
# ---------------------------------------------------------------------------

# thresholds. Op-level sites ("op/<name>") legitimately trace once per
# distinct layer shape class while a deep network builds — breadth, not
# churn — so they stay info until the count looks like a data-driven
# shape explosion. Step-level sites (the hapi donated train step, user
# jits) have ONE expected signature per dataset: any repeated
# shape/dtype retrace there is the bucket-your-data bug.
_OP_SHAPE_INFO = 8
_OP_SHAPE_WARN = 32
_STEP_CHURN = 2
_FROZEN_CHURN = 2

# per-cause counts already reported by earlier analyze() runs in this
# process: each run reports only the DELTA since the previous one, so a
# report on target X never re-attributes another model's history (a
# long-lived notebook would otherwise see every old model's churn in
# every new report). A count that went DOWN means trace_probe.reset()
# ran — treat the site as fresh.
_reported: dict = {}


def _delta_sites(sites: dict) -> dict:
    out = {}
    for name, rec in sites.items():
        causes = rec.get("causes", {})
        seen = _reported.get(name, {})
        delta = {}
        for c, n in causes.items():
            prev = seen.get(c, 0)
            d = n - prev if n >= prev else n
            if d > 0:
                delta[c] = d
        if delta:
            out[name] = {"traces": rec.get("traces", 0), "causes": delta}
        _reported[name] = dict(causes)
    return out


@register_pass("recompile-churn")
def recompile_churn_pass(ctx: AnalysisContext) -> List[Finding]:
    """Why retraces fired, from the trace_probe site registry
    (framework/trace_probe.py) — every eager-op jit wrapper and the hapi
    donated train step record the signature they were traced with, and a
    re-trace is classified shape / dtype / static_arg / frozen_set /
    structure at trace time. This pass turns per-site counts into
    findings — scoped to retraces SINCE THE LAST analyze() run in this
    process; the raw cumulative ``dispatch/retrace_cause`` counters stay
    visible in monitor/Prometheus either way."""
    out: List[Finding] = []
    sites = _delta_sites(ctx.retrace_sites or {})
    total = 0
    cause_totals: dict = {}
    for name, rec in sites.items():
        causes = rec.get("causes", {})
        for c, n in causes.items():
            cause_totals[c] = cause_totals.get(c, 0) + n
            total += n
        is_op_site = name.startswith("op/")
        shape_n = causes.get("shape", 0)
        if is_op_site and shape_n >= _OP_SHAPE_INFO:
            out.append(Finding(
                pass_id="recompile-churn",
                severity="warning" if shape_n >= _OP_SHAPE_WARN
                else "info",
                message=(f"{name} re-traced {shape_n}x on new shape "
                         f"classes since the last analysis — each is a "
                         f"fresh XLA compile (expected once per layer "
                         f"shape; a count that keeps growing across "
                         f"steps is data-driven churn)"),
                fix_hint=("bucket variable-length inputs "
                          "(io.BucketedBatchSampler) or pad to a fixed "
                          "shape set; the persistent compile cache only "
                          "amortizes across runs, not shapes")))
        if not is_op_site and shape_n >= _STEP_CHURN:
            out.append(Finding(
                pass_id="recompile-churn", severity="warning",
                message=(f"{name} re-traced {shape_n}x on batch shape "
                         f"changes — the whole step recompiles each "
                         f"time"),
                fix_hint=("bucket variable-length inputs "
                          "(io.BucketedBatchSampler), pad, or pin "
                          "batch_size with drop_last=True")))
        if not is_op_site and causes.get("dtype", 0) >= _STEP_CHURN:
            out.append(Finding(
                pass_id="recompile-churn", severity="warning",
                message=(f"{name} re-traced {causes['dtype']}x on dtype "
                         f"changes (e.g. an f32 batch after bf16 "
                         f"warmup)"),
                fix_hint="pin the input dtype at the loader"))
        if causes.get("frozen_set", 0) >= _FROZEN_CHURN:
            out.append(Finding(
                pass_id="recompile-churn", severity="warning",
                message=(f"{name}: the frozen-parameter set changed "
                         f"{causes['frozen_set']}x — every flip re-traces "
                         f"the donated train step and reconciles "
                         f"optimizer slots"),
                fix_hint=("batch stop_gradient flips (progressive "
                          "unfreezing per phase, not per step)")))
    if total:
        detail = ", ".join(f"{c}={n}"
                           for c, n in sorted(cause_totals.items()))
        out.append(Finding(
            pass_id="recompile-churn", severity="info",
            message=(f"{total} retrace(s) across {len(sites)} trace "
                     f"site(s) since the last analysis: {detail}"),
            fix_hint=None))
    return out

# ---------------------------------------------------------------------------
# 7. static-memory (ISSUE 18)
# ---------------------------------------------------------------------------

@register_pass("static-memory")
def static_memory_pass(ctx: AnalysisContext) -> List[Finding]:
    """Donation-aware liveness scan (analysis/liveness.py): one info
    finding carrying ``static_peak_bytes`` and the fattest program
    point. Always info — the BUDGET verdict belongs to the callers
    (``GenerationEngine(hbm_budget_bytes=)``, ``--budget``), which hold
    the device context this pass does not."""
    if ctx.closed_jaxpr is None:
        return []
    from . import liveness
    rep = liveness.jaxpr_liveness(ctx.closed_jaxpr, ctx.donated_invars,
                                  top_k=3)
    pk = rep.peak
    return [Finding(
        pass_id="static-memory", severity="info",
        message=(f"static peak {rep.static_peak_bytes:,} B live "
                 f"(args {rep.arg_bytes:,} B, {rep.donated_bytes:,} B "
                 f"donated; fattest point: "
                 f"{pk.primitive if pk else 'n/a'} at "
                 f"{(pk.source if pk else None) or 'unknown source'})"),
        source=pk.source if pk else None,
        primitive=pk.primitive if pk else None,
        data=rep.as_dict())]


# ---------------------------------------------------------------------------
# 8. donation-miss (ISSUE 18; supersedes the boolean dead-donation check)
# ---------------------------------------------------------------------------

@register_pass("donation-miss")
def donation_miss_pass(ctx: AnalysisContext) -> List[Finding]:
    """Donation decisions priced in bytes.

    (a) A large invar (>= liveness.DONATION_MISS_MIN_BYTES) that dies
    before the program ends but is NOT donated: warning carrying the
    ``static_peak_bytes`` reduction donating it would buy — computed by
    an honest liveness re-scan, not a heuristic, so an invar whose
    lifetime spans the peak anyway is never flagged. (b) A donated
    invar the program never reads (the old donation-safety boolean
    dead-donation warning, now here with its bytes)."""
    if ctx.closed_jaxpr is None:
        return []
    from . import liveness
    out: List[Finding] = []
    for m in liveness.donation_misses(ctx.closed_jaxpr,
                                      ctx.donated_invars):
        if m["kind"] == "dead":
            out.append(Finding(
                pass_id="donation-miss", severity="warning",
                message=(f"donated input #{m['argnum']} "
                         f"({m['bytes']:,} B) is never read by the "
                         f"computation (dead donation)"),
                fix_hint="stop passing (and donating) the unused value",
                data=m))
        else:
            out.append(Finding(
                pass_id="donation-miss", severity="warning",
                message=(f"input #{m['argnum']} ({m['bytes']:,} B) dies "
                         f"before the program ends but is not donated — "
                         f"donating it would cut static peak memory by "
                         f"{m['saving_bytes']:,} B"),
                source=m["last_use_source"],
                fix_hint=(f"add argnum {m['argnum']} to donate_argnums "
                          f"(the caller must not reuse the buffer after "
                          f"the call)"),
                data=m))
    return out


# ---------------------------------------------------------------------------
# 9. sharding-consistency (ISSUE 18)
# ---------------------------------------------------------------------------

# an array entering a shard_map fully replicated below this size is a
# rounding error per device; above it, the per-device copy is worth a
# finding (embedding tables, block pools).
SHARDING_REPLICATED_MIN_BYTES = 1 << 20


def _mesh_axis_sizes(mesh) -> dict:
    try:
        return dict(mesh.shape)
    except Exception:
        try:
            return {a: int(s) for a, s in
                    zip(mesh.axis_names, mesh.devices.shape)}
        except Exception:
            return {}


def _collective_axes(eqn):
    name = eqn.primitive.name
    if name == "psum":
        return _axis_key(eqn.params.get("axes", ()))
    if name in ("reduce_scatter", "all_gather", "all_to_all",
                "ppermute", "pmax", "pmin"):
        return _axis_key(eqn.params.get("axis_name", ()))
    return None


@register_pass("sharding-consistency")
def sharding_consistency_pass(ctx: AnalysisContext) -> List[Finding]:
    """Static checks inside shard_map regions (the dp x mp composition
    bug class from "Automatic Cross-Replica Sharding"):

    * every collective's axis name must exist on the shard_map's mesh
      (error — an axis the mesh does not carry reduces over nothing);
    * a reduce_scatter inside the body must be closed by an all_gather
      over the SAME (axis, dimension, tiled) triple — the PR-10
      collective-pairing structure, scoped to the sharded region where
      the mesh context makes the message precise (error);
    * an array >= SHARDING_REPLICATED_MIN_BYTES entering the shard_map
      with a fully-replicated spec (empty in_names) costs its FULL
      bytes on EVERY device — warning with the per-device cost and the
      saving the largest mesh axis would buy."""
    if ctx.closed_jaxpr is None:
        return []
    from .liveness import aval_bytes
    out: List[Finding] = []
    for eqn in iter_eqns(ctx.closed_jaxpr):
        if eqn.primitive.name != "shard_map":
            continue
        mesh = eqn.params.get("mesh")
        axis_sizes = _mesh_axis_sizes(mesh)
        src = eqn_source(eqn)
        body = eqn.params.get("jaxpr")
        if body is None:
            continue
        if hasattr(body, "jaxpr"):
            body = body.jaxpr

        # (1) + (2): the body's collectives, in program order
        rs, ag = [], []
        for pos, e in enumerate(iter_eqns(body)):
            axes = _collective_axes(e)
            if axes is None:
                continue
            unknown = [a for a in axes
                       if isinstance(a, str) and a not in axis_sizes]
            if unknown:
                out.append(Finding(
                    pass_id="sharding-consistency", severity="error",
                    message=(f"{e.primitive.name} over axis "
                             f"{unknown[0]!r} inside shard_map, but the "
                             f"mesh only carries "
                             f"{sorted(axis_sizes) or 'no axes'}"),
                    source=eqn_source(e) or src,
                    primitive=e.primitive.name,
                    fix_hint="name a mesh axis (Mesh(..., axis_names=))"))
            if e.primitive.name == "reduce_scatter":
                rs.append((pos, e))
            elif e.primitive.name == "all_gather":
                ag.append((pos, e))

        def _ag_key(g):
            return (_axis_key(g.params.get("axis_name")),
                    int(g.params.get("all_gather_dimension", 0)),
                    bool(g.params.get("tiled", False)))

        unconsumed = list(ag)
        for rs_pos, e in rs:
            key = (_axis_key(e.params.get("axis_name")),
                   int(e.params.get("scatter_dimension", 0)),
                   bool(e.params.get("tiled", False)))
            match = next((i for i, (p, g) in enumerate(unconsumed)
                          if p > rs_pos and _ag_key(g) == key), None)
            if match is not None:
                unconsumed.pop(match)
                continue
            later = [_ag_key(g) for p, g in unconsumed if p > rs_pos]
            have = ", ".join(
                f"axis={k[0]} dim={k[1]} tiled={k[2]}" for k in later) \
                or "none"
            out.append(Finding(
                pass_id="sharding-consistency", severity="error",
                message=(f"reduce_scatter over axis {key[0]} (dim="
                         f"{key[1]}, tiled={key[2]}) inside shard_map "
                         f"on mesh {axis_sizes} is not closed by a "
                         f"matching all_gather (later gathers: {have}) "
                         f"— the PR-10 pairing contract, scoped to the "
                         f"sharded region"),
                source=eqn_source(e) or src,
                primitive="reduce_scatter",
                fix_hint=("all_gather over the same axis/dimension/"
                          "tiling before leaving the shard_map body")))

        # (3): large fully-replicated operands
        in_names = eqn.params.get("in_names") or ()
        for k, (v, names) in enumerate(zip(eqn.invars, in_names)):
            if names:                      # partitioned on some axis
                continue
            b = aval_bytes(getattr(v, "aval", None))
            if b < SHARDING_REPLICATED_MIN_BYTES:
                continue
            biggest = max(axis_sizes.values()) if axis_sizes else 1
            out.append(Finding(
                pass_id="sharding-consistency", severity="warning",
                message=(f"operand #{k} ({b:,} B) enters shard_map "
                         f"fully replicated: {b:,} B resident on EVERY "
                         f"device of mesh {axis_sizes} — sharding its "
                         f"largest dim over the biggest axis would cut "
                         f"the per-device cost to ~{b // biggest:,} B"),
                source=src, primitive="shard_map",
                fix_hint=("give the operand a PartitionSpec over a mesh "
                          "axis (in_specs=P('mp', ...)), or keep small/"
                          "genuinely-shared state replicated on "
                          "purpose"),
                data={"argnum": k, "bytes": b,
                      "per_device_sharded_bytes": b // biggest}))
    return out
