"""Program linter core: Findings, the pass registry, and the analyze()
driver.

Reference analog: the reference's IR-pass layer
(paddle/fluid/framework/ir — ``Pass::Apply`` over a ProgramDesc graph,
registered via ``REGISTER_PASS``) and the InferMeta pre-flight checks.
TPU-native stance: the IR *is* the jaxpr. ``analyze()`` closed-jaxpr-
traces a callable (or replays a captured static Program) WITHOUT
compiling or executing it, then runs a pipeline of registered passes
over the trace; each pass emits structured :class:`Finding`s carrying
severity, eqn provenance (file:line of the op that produced the value)
and a fix hint. The properties checked are exactly the ones that are
statically derivable from the traced program — the same argument that
makes redistribution cost readable from shardings (arXiv:2112.01075)
and weight-update structure readable from the grad graph
(arXiv:2004.13336).

Observability contract: every run bumps ``analysis/runs`` and
``analysis/findings`` (+ per-severity and per-pass counters) and records
an ``analysis/pass_ms/<pass>`` histogram in framework/monitor.py, so the
linter's own cost and yield are visible in ``bench.py --dry-run`` and
the Prometheus exposition like any other subsystem.
"""
from __future__ import annotations

import time
import traceback as _tb
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from ..framework.monitor import stat_add, stat_observe
from ..profiler import span as _prof

__all__ = ["Finding", "Report", "AnalysisError", "register_pass",
           "all_passes", "analyze", "AnalysisContext", "iter_eqns",
           "eqn_source", "is_structural_zero", "SEVERITIES"]

# ordered weakest-first; rank index is the comparison key
SEVERITIES = ("info", "warning", "error")


@dataclass
class Finding:
    """One diagnosed program property (≙ a pass's graph-viz annotation in
    the reference IR layer, made machine-readable)."""

    pass_id: str
    severity: str               # "info" | "warning" | "error"
    message: str
    source: Optional[str] = None      # "file:line (fn)" eqn provenance
    primitive: Optional[str] = None   # offending jaxpr primitive, if any
    fix_hint: Optional[str] = None
    data: Optional[dict] = None       # machine-readable payload (bytes
                                      # figures etc.) for --json consumers

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"bad severity {self.severity!r}")

    def rank(self) -> int:
        return SEVERITIES.index(self.severity)


class AnalysisError(RuntimeError):
    """Raised by error-mode integrations (``Model.fit(analyze='error')``)
    when a run produces error-severity findings. Carries the report."""

    def __init__(self, report: "Report"):
        self.report = report
        errs = report.errors()
        super().__init__(
            f"static analysis found {len(errs)} error-severity "
            f"finding(s) in {report.target}:\n{report.table()}")


@dataclass
class Report:
    """All findings of one analyze() run, renderable as a table."""

    target: str
    findings: List[Finding] = field(default_factory=list)
    n_eqns: int = 0
    passes_run: List[str] = field(default_factory=list)

    def by_severity(self, severity: str) -> List[Finding]:
        return [f for f in self.findings if f.severity == severity]

    def errors(self) -> List[Finding]:
        return self.by_severity("error")

    def warnings(self) -> List[Finding]:
        return self.by_severity("warning")

    def ok(self) -> bool:
        """True when no error-severity findings (the pre-flight gate)."""
        return not self.errors()

    def table(self) -> str:
        """Human-readable findings table (worst first)."""
        if not self.findings:
            return (f"analysis of {self.target}: clean "
                    f"({self.n_eqns} eqns, "
                    f"passes: {', '.join(self.passes_run) or 'none'})")
        ordered = sorted(self.findings, key=lambda f: -f.rank())
        rows = [(f.severity.upper(), f.pass_id, f.source or "-",
                 f.message) for f in ordered]
        widths = [max(len(r[i]) for r in rows) for i in range(3)]
        lines = [f"analysis of {self.target}: "
                 f"{len(self.errors())} error(s), "
                 f"{len(self.warnings())} warning(s), "
                 f"{len(self.by_severity('info'))} info"]
        for (sev, pid, src, msg), f in zip(rows, ordered):
            lines.append(f"  {sev:<{widths[0]}}  {pid:<{widths[1]}}  "
                         f"{src:<{widths[2]}}  {msg}")
            if f.fix_hint:
                pad = " " * (6 + widths[0])
                lines.append(f"{pad}hint: {f.fix_hint}")
        return "\n".join(lines)

    def __repr__(self):
        return (f"<Report {self.target}: {len(self.findings)} findings "
                f"({len(self.errors())} errors)>")


# ---------------------------------------------------------------------------
# pass registry (≙ REGISTER_PASS in paddle/fluid/framework/ir/pass.h)
# ---------------------------------------------------------------------------

_PASSES: Dict[str, Callable] = {}


def register_pass(pass_id: str):
    """Register ``fn(ctx) -> iterable[Finding]`` under ``pass_id``.
    Passes run in registration order; a pass that needs a facility the
    context lacks (no jaxpr, no grad info) must return [] rather than
    raise."""

    def deco(fn):
        _PASSES[pass_id] = fn
        return fn

    return deco


def all_passes() -> List[str]:
    return list(_PASSES)


@dataclass
class AnalysisContext:
    """Everything a pass may inspect. Fields are None when the driver
    could not (or was not asked to) produce them."""

    target_name: str
    closed_jaxpr: Any = None          # jax ClosedJaxpr of the target
    trace_error: Any = None           # concretization exc caught in trace
    trace_error_source: Optional[str] = None
    args: tuple = ()                  # original (pre-unwrap) args
    donate_argnums: tuple = ()
    donated_invars: Any = None        # list[bool] over flat invars
    grad: Any = None                  # {"jaxpr", "names", "trainable"}
    counters: Any = None              # monitor.all_stats() snapshot
    retrace_sites: Any = None         # trace_probe.snapshot()


# ---------------------------------------------------------------------------
# jaxpr utilities shared by the passes
# ---------------------------------------------------------------------------

def _sub_jaxprs(eqn):
    for v in eqn.params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for x in vals:
            if hasattr(x, "jaxpr") and hasattr(x.jaxpr, "eqns"):
                yield x.jaxpr        # ClosedJaxpr
            elif hasattr(x, "eqns"):
                yield x              # raw Jaxpr


def iter_eqns(jaxpr) -> Iterable:
    """Yield every eqn of ``jaxpr`` recursively, descending into
    call/control-flow sub-jaxprs (pjit, scan, while, cond, custom_vjp)."""
    if hasattr(jaxpr, "jaxpr"):      # ClosedJaxpr -> Jaxpr
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def eqn_source(eqn) -> Optional[str]:
    """'file:line (fn)' provenance of one eqn, best-effort across jax
    versions. The analyzer's own tracing wrappers are not provenance."""
    try:
        from jax._src import source_info_util
        s = source_info_util.summarize(eqn.source_info)
        return None if "paddle_tpu/analysis" in s else s
    except Exception:
        return None


_TRANSPARENT = frozenset({
    "broadcast_in_dim", "convert_element_type", "reshape", "squeeze",
    "transpose", "copy", "expand_dims", "stop_gradient",
})


def is_structural_zero(jaxpr, var) -> bool:
    """True when ``var`` is produced by a chain of shape/dtype-only ops
    terminating in a literal 0 — the exact way jax AD materializes a
    symbolic-zero cotangent (``broadcast_in_dim [0.0]``). Constant but
    NONzero values (e.g. the grad of ``p.sum()``) are not zeros, so a
    linear loss never false-positives."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    producers = {}
    for eqn in jaxpr.eqns:
        for ov in eqn.outvars:
            producers[ov] = eqn
    for _ in range(64):  # chain bound; real zero chains are 1-2 eqns
        if hasattr(var, "val"):  # Literal
            try:
                return not np.any(np.asarray(var.val))
            except Exception:
                return False
        eqn = producers.get(var)
        if eqn is None or eqn.primitive.name not in _TRANSPARENT:
            return False
        var = eqn.invars[0]
    return False


# ---------------------------------------------------------------------------
# tracing helpers
# ---------------------------------------------------------------------------

def _concretization_errors():
    import jax.errors as je
    return tuple(
        getattr(je, n) for n in
        ("ConcretizationTypeError", "TracerArrayConversionError",
         "TracerBoolConversionError", "TracerIntegerConversionError")
        if hasattr(je, n))


def _blame_frame(exc) -> Optional[str]:
    """Deepest traceback frame that is user code — not jax internals,
    not this package — so a ConcretizationError points at the
    ``.numpy()`` call site, not at jax's tracer plumbing."""
    frames = _tb.extract_tb(exc.__traceback__)

    def is_jax(f):
        return "/jax/" in f or "/jax_" in f or "/jaxlib/" in f \
            or "/site-packages/jax" in f

    def is_ours(f):
        return "paddle_tpu/analysis" in f

    best = None
    for fr in frames:
        if is_jax(fr.filename) or is_ours(fr.filename):
            continue
        best = fr  # keep the deepest acceptable frame
    # prefer a frame OUTSIDE the framework itself when one exists (the
    # user's line beats framework/tensor.py's np.asarray internals)
    user = None
    for fr in frames:
        if is_jax(fr.filename) or is_ours(fr.filename) \
                or "paddle_tpu/" in fr.filename:
            continue
        user = fr
    fr = user or best
    if fr is None:
        return None
    return f"{fr.filename}:{fr.lineno} ({fr.name})"


def _tensor_type():
    from ..framework.tensor import Tensor
    return Tensor


def _trace_callable(fn, args, static_argnums=()):
    """make_jaxpr over ``fn`` with Tensor-aware arg/result handling.
    Returns (closed_jaxpr, donated_invars, arg_leaf_ranges)."""
    import jax

    Tensor = _tensor_type()
    static_argnums = tuple(static_argnums)
    dyn = [a for i, a in enumerate(args) if i not in static_argnums]
    statics = {i: a for i, a in enumerate(args) if i in static_argnums}

    is_t = lambda x: isinstance(x, Tensor)
    flat, treedef = jax.tree_util.tree_flatten(tuple(dyn), is_leaf=is_t)
    mask = [is_t(x) for x in flat]
    leaves = [x._data if m else x for x, m in zip(flat, mask)]

    # per-ORIGINAL-arg leaf ranges (None for static args) so
    # donate_argnums — which live in the same index space jax.jit uses,
    # counting statics — map onto flat invar positions correctly even
    # with a static argnum before a donated one
    ranges = []
    pos = 0
    for i, a in enumerate(args):
        if i in statics:
            ranges.append(None)
            continue
        n = len(jax.tree_util.tree_flatten(a, is_leaf=is_t)[0])
        ranges.append((pos, pos + n))
        pos += n

    def unwrap(x):
        return x._data if isinstance(x, Tensor) else x

    def fn_flat(*xs):
        rewrapped = [Tensor(x, stop_gradient=True) if m else x
                     for x, m in zip(xs, mask)]
        call_dyn = list(jax.tree_util.tree_unflatten(treedef, rewrapped))
        call_args = []
        di = 0
        for i in range(len(args)):
            if i in statics:
                call_args.append(statics[i])
            else:
                call_args.append(call_dyn[di])
                di += 1
        out = fn(*call_args)
        return jax.tree_util.tree_map(unwrap, out, is_leaf=is_t)

    closed = jax.make_jaxpr(fn_flat)(*leaves)
    return closed, ranges


def _donated_invars(closed, donate_argnums, ranges):
    """Donation mask over the outer jaxpr's invars: the explicit
    donate_argnums argument wins; otherwise auto-detect a single
    top-level pjit eqn's donated_invars (analyzing an already-jitted fn
    sees its donation contract without being told)."""
    n = len(closed.jaxpr.invars)
    if donate_argnums:
        mask = [False] * n
        for argnum in donate_argnums:
            if argnum < len(ranges) and ranges[argnum] is not None:
                lo, hi = ranges[argnum]
                for i in range(lo, min(hi, n)):
                    mask[i] = True
        return mask
    eqns = closed.jaxpr.eqns
    if len(eqns) == 1 and eqns[0].primitive.name == "pjit":
        don = eqns[0].params.get("donated_invars")
        if don and any(don):
            # map the pjit eqn's donated invars back onto outer invars
            outer = {v: i for i, v in enumerate(closed.jaxpr.invars)}
            mask = [False] * n
            for v, d in zip(eqns[0].invars, don):
                if d and v in outer:
                    mask[outer[v]] = True
            return mask
    return None


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------

def _is_program(target) -> bool:
    return hasattr(target, "_forward_env") and hasattr(target, "_nodes")


def _program_callable(program):
    """A pure (feeds, params) -> outputs replay of a captured static
    Program, traceable without executing (the Executor pre-flight)."""
    import jax.numpy as jnp

    feed_avals = {}
    for name, tid in program._feeds.items():
        t = program._vars[tid]
        feed_avals[name] = jnp.zeros(tuple(t._data.shape), t._data.dtype)
    params = {n: p._data for n, p in program._params.items()}

    def replay(feeds, params):
        env = program._forward_env(feeds, params)
        # every produced value is a root: nothing gets pruned, so the
        # passes see the whole recorded graph
        return [env[tid] for node in program._nodes
                for tid in node.out_ids if tid in env]

    return replay, (feed_avals, params)


def _translated_callable(layer):
    """Trace a jit.load artifact (TranslatedLayer) from its saved specs."""
    import jax

    avals = []
    for s in layer.input_specs:
        shape = tuple(1 if d in (-1, None) else int(d)
                      for d in s.get("shape", ()))
        avals.append(jax.ShapeDtypeStruct(shape, np.dtype(
            s.get("dtype", "float32"))))
    if not avals:
        raise ValueError(
            "saved artifact has no input_specs metadata; pass avals "
            "explicitly: analyze(layer._exported.call, *avals)")
    return layer._exported.call, tuple(avals)


def analyze(target, *args, donate_argnums=(), static_argnums=(),
            passes: Optional[Sequence[str]] = None, name: Optional[str]
            = None, grad: Any = None) -> Report:
    """Trace ``target`` (callable, jitted callable, captured static
    Program, or jit.load TranslatedLayer) and run the analysis pass
    pipeline over the resulting jaxpr WITHOUT compiling or executing it.

    ``args`` are example inputs — Tensors, arrays or ShapeDtypeStructs
    (ignored for Programs, which carry their own feed specs).
    ``donate_argnums`` declares the donation contract to the
    donation-safety pass (auto-detected from an already-jitted target).
    ``grad`` optionally supplies {"jaxpr", "names", "trainable"} for the
    dead/frozen-grad pass (see ``analyze_model``, which builds it from a
    hapi Model). Returns a :class:`Report`; never executes device code.
    """
    from ..framework import trace_probe
    from ..framework.monitor import all_stats

    t_run = time.perf_counter()
    if _is_program(target):
        fn, fn_args = _program_callable(target)
        tname = name or "static.Program"
        donate_argnums = ()
    elif hasattr(target, "_exported") and hasattr(target, "input_specs"):
        fn, fn_args = _translated_callable(target)
        tname = name or "jit.load artifact"
    elif callable(target) or target is None:
        fn, fn_args = target, args
        tname = name or getattr(target, "__name__", None) or repr(target)
    else:
        raise TypeError(f"cannot analyze {type(target).__name__}")

    ctx = AnalysisContext(target_name=tname, args=fn_args,
                          donate_argnums=tuple(donate_argnums),
                          grad=grad, counters=all_stats(),
                          retrace_sites=trace_probe.snapshot())
    report = Report(target=tname)

    if fn is not None:
        with _prof.record(f"analysis/trace/{tname}", "analysis"):
            try:
                closed, ranges = _trace_callable(fn, fn_args,
                                                 static_argnums)
                ctx.closed_jaxpr = closed
                ctx.donated_invars = _donated_invars(
                    closed, ctx.donate_argnums, ranges)
                report.n_eqns = sum(1 for _ in iter_eqns(closed))
            except _concretization_errors() as e:
                ctx.trace_error = e
                ctx.trace_error_source = _blame_frame(e)

    selected = list(passes) if passes is not None else list(_PASSES)
    for pid in selected:
        p = _PASSES.get(pid)
        if p is None:
            raise KeyError(f"unknown analysis pass {pid!r}; "
                           f"registered: {all_passes()}")
        t0 = time.perf_counter()
        found = list(p(ctx))
        stat_observe(f"analysis/pass_ms/{pid}",
                     (time.perf_counter() - t0) * 1e3)
        report.passes_run.append(pid)
        report.findings.extend(found)

    stat_add("analysis/runs")
    stat_add("analysis/findings", len(report.findings))
    for f in report.findings:
        stat_add(f"analysis/findings/{f.severity}")
        stat_add(f"analysis/findings/{f.pass_id}")
    stat_observe("analysis/analyze_ms",
                 (time.perf_counter() - t_run) * 1e3)
    return report
