"""Repo self-lint: AST rules over ``paddle_tpu/`` itself.

The jaxpr passes check programs USERS build; these rules check the
framework's own source for the contracts the codebase documents but
Python cannot enforce (≙ the reference's tools/codestyle custom checks
+ cpplint rules for its own invariants):

* ``device-get-hot-path`` — no bare ``jax.device_get`` in hot-path
  modules (dispatch, tensor, monitor, the hapi step loop): every one is
  a blocking D2H sync per call. Sync points elsewhere (spmd state
  mirror, pipeline aggregation) are legitimate and stay unflagged.
* ``monitor-lock-contract`` — the monitor's writer hot path is lock-free
  BY CONTRACT (framework/monitor.py docstring): ``stat_add`` must not
  take ``_lock``, and no module outside monitor.py may import or touch
  its ``_lock``/``_stats``/``_hists`` internals.
* ``asarray-on-traced`` — inside a ``@register_op`` impl (which runs
  under jit unless registered ``jit=False``), ``np.asarray``/``np.array``
  on an op argument concretizes a tracer: TracerArrayConversionError at
  best, a silent constant-bake at worst. Nested host-callback bodies
  (pure_callback closures) shadow the name and are exempt.
* ``serving-host-sync`` — the continuous-batching decode loop
  (``paddle_tpu/serving/``, the paged memory manager ``serving/paging.py``
  included) must stay sync-free: ``jax.device_get``,
  ``.block_until_ready()`` (method or ``jax.block_until_ready`` module
  form) and ``.numpy()`` anywhere in the package are a per-step device
  stall. The single argued exception is the windowed token fetch
  (``serving/scheduler.py _fetch``), which carries the suppression.
* ``ops-handler-sync`` — the ops HTTP surface (``serving/opsserver.py``),
  the SLO plane (``serving/slo.py``) and the inference front door
  (``serving/frontdoor.py``) are scrape-only BY CONTRACT:
  handlers serve collector samples, host rings and host counters, and
  must never touch the device or block on the scheduler. On top of the
  ``serving-host-sync`` walk (which already covers both files as part
  of the package), this rule bans ANY ``jax.*``/``jnp.*`` call and the
  scheduler-blocking reads ``.result()``/``.item()`` there — a scrape
  that blocks on a stuck scheduler turns the monitoring plane into a
  second victim of the outage it exists to observe.
* ``memory-stats-hot-path`` — ``memory_stats()`` polling (a PjRt query
  per call) stays OFF the scheduler hot path: inside ``serving/`` the
  memory timeline is fed by host-only ``profiler.memory.mark()``
  stamps; device polling belongs to the tracker's background sampler
  thread (``profiler/memory.py``) and windowed surfaces like fit's
  flush.
* ``numerics-host-sync`` — the training numerics layer
  (``profiler/numerics.py``) exists to REPLACE the reference's per-op
  host sweep with audits fetched only at fit's flush windows, so the
  module itself must never sync: ``jax.device_get``, ``.item()``,
  ``.numpy()`` and ``.block_until_ready()`` are banned there — the
  fetch lives in ``hapi/model.py _flush_window`` (behind the window's
  existing blocking loss fetch), and the recorder receives numpy.
* ``pallas-block-tiling`` — Mosaic's TPU block-shape rule, statically:
  inside ``ops/``, a ``pl.BlockSpec`` whose block tuple carries a
  LITERAL second-to-last dim not divisible by 8, or a literal last dim
  neither divisible by 128 nor >= 8-aligned... — precisely: the
  second-to-last block dim must be divisible by 8 (or equal the array
  dim) and the last must be 128-aligned (or the full array dim). The
  AST cannot see array shapes, so literal dims that fail the divisible
  test are flagged and a spec that is legal because the block IS the
  full array dim carries a ``# lint: ok`` suppression with the argument
  adjacent. This is the exact ``(1, 128)``-block crash BENCH_r02
  recorded on hardware (flash-attention LSE output), turned into a
  standing static check. SMEM specs and shapeless (whole-array) specs
  are exempt; dynamic dims (names/expressions) are trusted — the
  kernels derive them from array shapes.

* ``metric-naming`` — literal metric names at monitor
  (``stat_add``/``stat_observe``) and metrics-registry
  (``metrics.inc``/``observe``/``set_gauge``) write sites are lowercase
  snake_case path segments, and a name that says it carries time or
  size says the unit: ``_ms``/``_bytes``, never ``_time``/``_secs``/
  ``_mb``. One process's metrics feed one Grafana; a ``*_secs`` sample
  landing in a ``*_ms`` panel misreads by 1000x and a CamelCase name
  breaks every PromQL regex written against the snake_case rest.

* ``analysis-no-device`` — the static planner (``paddle_tpu/analysis/``)
  answers "will it fit?" BEFORE any compile, from jaxpr avals alone
  (ISSUE 18): ``jax.jit``, ``.compile()`` (``re.compile`` exempt),
  ``device_put`` and ``block_until_ready`` are banned in the package —
  an admission gate that compiles has already paid the cost it gates.

Suppress a finding with a trailing ``# lint: ok`` comment on the line
(used only where a human has argued the exception in an adjacent
comment). Run: ``python -m paddle_tpu.analysis --selflint`` or the
tier-1 test (tests/test_selflint.py).
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import List, Optional, Sequence

__all__ = ["LintFinding", "lint_source", "lint_repo", "HOT_PATH_MODULES"]

# modules where a stray device_get is a per-call sync on the hot path
HOT_PATH_MODULES = (
    "framework/dispatch.py", "framework/tensor.py", "framework/monitor.py",
    "framework/trace_probe.py", "hapi/model.py", "ops/registry.py",
)

_MONITOR_PRIVATE = {"_lock", "_stats", "_hists"}


@dataclass
class LintFinding:
    rule: str
    path: str
    line: int
    message: str

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _suppressed(source_lines: Sequence[str], lineno: int) -> bool:
    try:
        return "# lint: ok" in source_lines[lineno - 1]
    except IndexError:
        return False


def _is_jax_device_get(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "device_get"
            and isinstance(f.value, ast.Name) and f.value.id == "jax")


def _decorator_name(d) -> Optional[str]:
    if isinstance(d, ast.Call):
        d = d.func
    if isinstance(d, ast.Attribute):
        return d.attr
    if isinstance(d, ast.Name):
        return d.id
    return None


def _op_decorator(fn: ast.FunctionDef):
    """The @register_op(...) decorator Call of ``fn``, if any."""
    for d in fn.decorator_list:
        if _decorator_name(d) in ("register_op", "register_override") \
                and isinstance(d, ast.Call):
            return d
    return None


def _jit_disabled(dec: ast.Call) -> bool:
    for kw in dec.keywords:
        if kw.arg == "jit" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    return False


class _AsarrayVisitor(ast.NodeVisitor):
    """Flags np.asarray/np.array(<op param>) inside an op impl, honoring
    nested-function shadowing (host-callback closures redefine the
    name, which makes the call host-side and fine)."""

    def __init__(self, params, lines, path, findings):
        self.scopes = [set(params)]
        self.lines = lines
        self.path = path
        self.findings = findings

    def _params_of(self, node):
        a = node.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return set(names)

    def visit_FunctionDef(self, node):
        self.scopes.append(self._params_of(node))
        self.generic_visit(node)
        self.scopes.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self.scopes.append(self._params_of(node))
        self.generic_visit(node)
        self.scopes.pop()

    def visit_Call(self, node):
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in ("asarray", "array")
                and isinstance(f.value, ast.Name)
                and f.value.id in ("np", "numpy") and node.args
                and isinstance(node.args[0], ast.Name)):
            name = node.args[0].id
            # flagged only when the name is the OP's own parameter and no
            # nested scope shadows it
            if name in self.scopes[0] and not any(
                    name in s for s in self.scopes[1:]) \
                    and not _suppressed(self.lines, node.lineno):
                self.findings.append(LintFinding(
                    "asarray-on-traced", self.path, node.lineno,
                    f"np.{f.attr}({name}) on a traced op argument — "
                    f"concretizes under jit; use jnp, mark the op "
                    f"jit=False, or route through pure_callback"))
        self.generic_visit(node)


def _blockspec_literal_dims(node: ast.Call):
    """For a ``BlockSpec(...)`` call (attribute or bare-name form, the
    block tuple positional or via ``block_shape=``): the shape tuple's
    last two elements as ints where they are literals (None where
    dynamic), or None when the spec has no block tuple / is
    SMEM-space."""
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    if name != "BlockSpec":
        return None
    shape = node.args[0] if node.args else None
    for kw in node.keywords:
        if kw.arg == "memory_space" and isinstance(kw.value, ast.Attribute) \
                and kw.value.attr == "SMEM":
            return None            # scalar memory: no (8, 128) tiling
        if kw.arg == "block_shape" and shape is None:
            shape = kw.value
    if not isinstance(shape, ast.Tuple) or len(shape.elts) < 2:
        return None

    def lit(e):
        return e.value if isinstance(e, ast.Constant) \
            and isinstance(e.value, int) else None

    return lit(shape.elts[-2]), lit(shape.elts[-1])


# metric-emitting call sites the metric-naming rule inspects: the
# monitor writers anywhere, and the metrics-registry writers when
# called through a module alias that names the registry
_MONITOR_WRITERS = ("stat_add", "stat_observe")
_REGISTRY_WRITERS = ("inc", "set_gauge", "observe")
# a name part ending in one of these carries a time/size quantity with
# NO unit: the naming contract wants _ms / _bytes so dashboards never
# have to guess (and never mix seconds into a *_ms panel)
_UNITLESS_TIME_SUFFIXES = ("_time", "_latency", "_duration", "_secs",
                           "_seconds")
_NON_BYTE_SIZE_SUFFIXES = ("_kb", "_mb", "_gb", "_kib", "_mib", "_gib")
_METRIC_CHARSET = frozenset("abcdefghijklmnopqrstuvwxyz0123456789_/")


def _metric_leading_literal(arg) -> "Optional[tuple]":
    """(leading_literal, is_full_literal) of a metric-name argument, or
    None when nothing literal leads it (a fully dynamic name is the
    caller's problem — the registry validates at write time)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, True
    if isinstance(arg, ast.JoinedStr) and arg.values:
        head = arg.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value, False
    return None


def _metric_name_finding(node: ast.Call) -> Optional[str]:
    """The metric-naming rule body: literal metric names at monitor /
    registry write sites must be lowercase snake_case path segments
    (``[a-z0-9_/]``; dimensions belong in labels or the per-key path
    tail, units in a ``_ms``/``_bytes`` suffix), and a name that SAYS
    it carries time or size must say the unit (``op_time`` -> error,
    ``op_time_ms`` -> fine; ``_gb`` -> ``_bytes``)."""
    f = node.func
    fname = f.attr if isinstance(f, ast.Attribute) else \
        (f.id if isinstance(f, ast.Name) else None)
    if fname in _MONITOR_WRITERS:
        pass
    elif fname in _REGISTRY_WRITERS:
        # only when addressed through a metrics-registry alias —
        # .observe()/.inc() are common method names elsewhere
        v = getattr(f, "value", None)
        if not (isinstance(v, ast.Name) and "metric" in v.id.lower()):
            return None
    else:
        return None
    if not node.args:
        return None
    lit = _metric_leading_literal(node.args[0])
    if lit is None:
        return None
    text, full = lit
    bad = sorted({c for c in text if c not in _METRIC_CHARSET})
    if bad:
        return (f"metric name {text!r} violates the naming contract "
                f"(snake_case [a-z0-9_] path segments; offending "
                f"chars: {''.join(bad)!r}) — dimensions go in labels "
                f"or the per-key path tail, never CamelCase/-/spaces")
    if full:
        tail = text.rsplit("/", 1)[-1]
        for suf in _UNITLESS_TIME_SUFFIXES:
            if tail.endswith(suf):
                return (f"metric name {text!r} carries a time quantity "
                        f"without its unit: suffix it _ms (the naming "
                        f"contract — a *_secs sample in a *_ms panel "
                        f"is a 1000x lie)")
        for suf in _NON_BYTE_SIZE_SUFFIXES:
            if tail.endswith(suf):
                return (f"metric name {text!r} bakes a scaled size unit "
                        f"into the name: record raw _bytes and let the "
                        f"dashboard scale")
    return None


def lint_source(path: str, source: str, relpath: str) -> List[LintFinding]:
    """Lint one file's source. ``relpath`` is the path relative to the
    package root (rule applicability is keyed on it)."""
    findings: List[LintFinding] = []
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [LintFinding("parse", path, e.lineno or 0, str(e))]
    lines = source.splitlines()
    rel = relpath.replace(os.sep, "/")
    in_monitor = rel.endswith("framework/monitor.py")
    hot = any(rel.endswith(m) for m in HOT_PATH_MODULES)
    # the serving PACKAGE only — inference/serving.py (the gather-and-run
    # batcher) blocks its callers by design and is not in scope
    in_serving = rel.startswith("serving/")
    # the scrape-only ops surface: HTTP handlers + the SLO plane
    in_ops_surface = rel.endswith("serving/opsserver.py") \
        or rel.endswith("serving/slo.py") \
        or rel.endswith("serving/frontdoor.py")
    # Pallas kernels live in ops/ — BlockSpec tiling is checked there
    in_ops = rel.startswith("ops/")
    # the numerics audit module: host-pure over numpy BY CONTRACT
    in_numerics = rel.endswith("profiler/numerics.py")
    # the static planner: aval arithmetic only, never compile/device work
    in_analysis = rel.startswith("analysis/")

    for node in ast.walk(tree):
        # rule: analysis-no-device (the planner's fit-BEFORE-compile
        # contract: paddle_tpu/analysis/ answers memory questions from
        # jaxprs alone, so nothing in the package may trigger a compile
        # or touch the device)
        if in_analysis and isinstance(node, ast.Call):
            f = node.func
            banned = None
            if isinstance(f, ast.Attribute):
                recv = f.value
                recv_name = recv.id if isinstance(recv, ast.Name) else None
                if f.attr == "jit" and recv_name == "jax":
                    banned = "jax.jit"
                elif f.attr == "device_put":
                    banned = "device_put"
                elif f.attr == "block_until_ready":
                    banned = ".block_until_ready()"
                elif f.attr == "compile" and recv_name != "re":
                    banned = ".compile()"
            elif isinstance(f, ast.Name) and f.id == "device_put":
                banned = "device_put"
            if banned and not _suppressed(lines, node.lineno):
                findings.append(LintFinding(
                    "analysis-no-device", path, node.lineno,
                    f"{banned} inside paddle_tpu/analysis/: the static "
                    f"planner answers fit-BEFORE-compile from jaxpr "
                    f"avals alone — compiling or touching the device "
                    f"here would make the admission gate pay the cost "
                    f"it exists to avoid"))
        # rule: pallas-block-tiling (Mosaic (8, 128) block-shape law)
        if in_ops and isinstance(node, ast.Call):
            dims = _blockspec_literal_dims(node)
            if dims is not None and not _suppressed(lines, node.lineno):
                sub, lane = dims
                if sub is not None and (sub < 1 or sub % 8):
                    findings.append(LintFinding(
                        "pallas-block-tiling", path, node.lineno,
                        f"BlockSpec second-to-last block dim {sub} is "
                        f"not divisible by 8: Mosaic rejects the layout "
                        f"on TPU (the BENCH_r02 (1, 128) crash) unless "
                        f"it equals the array dim — if it provably "
                        f"does, argue it in an adjacent comment and "
                        f"suppress with '# lint: ok'"))
                if lane is not None and (lane < 1 or lane % 128):
                    findings.append(LintFinding(
                        "pallas-block-tiling", path, node.lineno,
                        f"BlockSpec last block dim {lane} is not "
                        f"128-aligned: Mosaic rejects the layout on TPU "
                        f"unless it equals the array dim — if it "
                        f"provably does, argue it in an adjacent "
                        f"comment and suppress with '# lint: ok'"))
        # rule: serving-host-sync (no host sync in the decode loop)
        if in_serving and isinstance(node, ast.Call):
            sync = None
            if _is_jax_device_get(node):
                sync = "jax.device_get"
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "block_until_ready" \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "jax":
                sync = "jax.block_until_ready"
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("block_until_ready", "numpy"):
                sync = f".{node.func.attr}()"
            if sync and not _suppressed(lines, node.lineno):
                findings.append(LintFinding(
                    "serving-host-sync", path, node.lineno,
                    f"{sync} in the serving package: the continuous-"
                    f"batching decode loop must stay async — route "
                    f"device reads through the single windowed fetch "
                    f"(serving/scheduler.py _fetch)"))
        # rule: ops-handler-sync (the scrape-only ops surface: no
        # device work, no scheduler-blocking reads — a monitoring
        # plane that blocks on what it monitors goes down with it)
        if in_ops_surface and isinstance(node, ast.Call):
            f = node.func
            bad = None
            if isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in ("jax", "jnp"):
                bad = f"{f.value.id}.{f.attr}"
            elif isinstance(f, ast.Attribute) \
                    and f.attr in ("result", "item", "block_until_ready",
                                   "numpy", "device_get"):
                bad = f".{f.attr}()"
            elif isinstance(f, ast.Name) and f.id == "device_get":
                bad = "device_get"
            if bad and not _suppressed(lines, node.lineno):
                findings.append(LintFinding(
                    "ops-handler-sync", path, node.lineno,
                    f"{bad} on the ops HTTP surface: handlers are "
                    f"scrape-only — no device fetches, no "
                    f"block_until_ready, no scheduler-blocking "
                    f"result()/item(); serve collector samples and "
                    f"host rings instead"))
        # rule: numerics-host-sync (the numerics audit module never
        # syncs — fetches belong to fit's flush window)
        if in_numerics and isinstance(node, ast.Call):
            sync = None
            if _is_jax_device_get(node):
                sync = "jax.device_get"
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "block_until_ready" \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "jax":
                sync = "jax.block_until_ready"
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("block_until_ready", "numpy",
                                           "item"):
                sync = f".{node.func.attr}()"
            if sync and not _suppressed(lines, node.lineno):
                findings.append(LintFinding(
                    "numerics-host-sync", path, node.lineno,
                    f"{sync} in the numerics audit module: the audit "
                    f"replaces the reference's per-op host sweep "
                    f"precisely by never syncing — device vectors are "
                    f"fetched ONLY at Model._flush_window (behind the "
                    f"window's existing loss fetch) and arrive here as "
                    f"numpy"))
        # rule: memory-stats-hot-path (no device memory polling in the
        # serving package — marks are host-only, the sampler thread
        # polls)
        if in_serving and isinstance(node, ast.Call):
            f = node.func
            poll = (isinstance(f, ast.Attribute)
                    and f.attr == "memory_stats") or \
                   (isinstance(f, ast.Name) and f.id == "memory_stats")
            if poll and not _suppressed(lines, node.lineno):
                findings.append(LintFinding(
                    "memory-stats-hot-path", path, node.lineno,
                    "memory_stats() polled in the serving package: a "
                    "PjRt stats query per scheduler cycle — stamp "
                    "host-only watermarks with profiler.memory.mark() "
                    "and leave polling to the tracker's sampler thread "
                    "(profiler/memory.py)"))
        # rule: device-get-hot-path
        if hot and isinstance(node, ast.Call) and _is_jax_device_get(node) \
                and not _suppressed(lines, node.lineno):
            findings.append(LintFinding(
                "device-get-hot-path", path, node.lineno,
                "bare jax.device_get in a hot-path module: a blocking "
                "D2H sync per call — return device values and flush in "
                "windows (Model._flush_window)"))

        # rule: monitor-lock-contract (outside monitor.py: no touching
        # its private state)
        if not in_monitor:
            bad = None
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.rsplit(".", 1)[-1] == "monitor":
                hit = [a.name for a in node.names
                       if a.name in _MONITOR_PRIVATE]
                bad = hit[0] if hit else None
            elif isinstance(node, ast.Attribute) \
                    and node.attr in _MONITOR_PRIVATE \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "monitor":
                bad = node.attr
            if bad and not _suppressed(lines, node.lineno):
                findings.append(LintFinding(
                    "monitor-lock-contract", path, node.lineno,
                    f"direct use of monitor.{bad}: the monitor's "
                    f"internals are private to its threading contract "
                    f"(framework/monitor.py docstring); use the "
                    f"stat_*/all_* API"))

        # rule: monitor-lock-contract (inside monitor.py: stat_add stays
        # lock-free)
        if in_monitor and isinstance(node, ast.FunctionDef) \
                and node.name == "stat_add":
            for sub in ast.walk(node):
                if isinstance(sub, ast.With) and any(
                        isinstance(item.context_expr, ast.Name)
                        and item.context_expr.id == "_lock"
                        for item in sub.items) \
                        and not _suppressed(lines, sub.lineno):
                    findings.append(LintFinding(
                        "monitor-lock-contract", path, sub.lineno,
                        "stat_add takes _lock: the writer hot path is "
                        "lock-free BY CONTRACT (module docstring) — a "
                        "lock per eager op dispatch serializes the "
                        "engine"))

        # rule: metric-naming (snake_case paths, unit-suffixed units)
        if isinstance(node, ast.Call):
            mfind = _metric_name_finding(node)
            if mfind and not _suppressed(lines, node.lineno):
                findings.append(LintFinding(
                    "metric-naming", path, node.lineno, mfind))

        # rule: asarray-on-traced (op impls that run under jit)
        if isinstance(node, ast.FunctionDef):
            dec = _op_decorator(node)
            if dec is not None and not _jit_disabled(dec):
                params = [p.arg for p in node.args.posonlyargs
                          + node.args.args]
                v = _AsarrayVisitor(params, lines, path, findings)
                for stmt in node.body:  # not node: the op fn's own
                    v.visit(stmt)       # params are scope 0, not a shadow

    return findings


def lint_repo(root: Optional[str] = None) -> List[LintFinding]:
    """Lint every .py file under the paddle_tpu package (or ``root``)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings: List[LintFinding] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root)
            with open(path, encoding="utf-8") as f:
                src = f.read()
            findings.extend(lint_source(path, src, rel))
    return findings
