"""CLI: ``python -m paddle_tpu.analysis <target>``.

Targets:

* ``module:attr`` — import ``module`` and resolve ``attr``. If calling
  ``attr()`` with no arguments returns ``(fn, example_args)`` (the
  ``__graft_entry__.entry`` convention) that pair is analyzed; otherwise
  ``attr`` itself is the target and ``--input`` specs supply the avals.
* a ``jit.save`` artifact prefix or directory (``m`` for ``m.pdmodel``)
  — loaded and analyzed from its saved input specs.

Options: ``--input dtype:d0,d1,...`` (repeatable), ``--donate 0,1``,
``--passes a,b``, ``--selflint`` (lint paddle_tpu's own source instead).
Exit status: 0 clean / findings below error, 1 error-severity findings
(or any self-lint finding) — usable as a CI gate.
"""
from __future__ import annotations

import argparse
import importlib
import os
import sys


_DTYPES = {"f32": "float32", "f64": "float64", "bf16": "bfloat16",
           "f16": "float16", "i32": "int32", "i64": "int64",
           "i8": "int8", "u8": "uint8", "bool": "bool"}


def _parse_input(spec: str):
    import jax
    import numpy as np
    if ":" in spec:
        dtype, _, dims = spec.partition(":")
    else:
        dtype, dims = "float32", spec
    dtype = _DTYPES.get(dtype, dtype)
    shape = tuple(int(d) for d in dims.replace("x", ",").split(",") if d)
    return jax.ShapeDtypeStruct(shape, np.dtype(dtype))


def _resolve(target: str):
    """-> (fn_or_obj, args or None, display name)."""
    if ":" in target and not os.path.exists(target.split(":")[0]):
        mod_name, _, attr = target.rpartition(":")
        sys.path.insert(0, os.getcwd())
        obj = getattr(importlib.import_module(mod_name), attr)
        if callable(obj):
            try:
                produced = obj()
            except TypeError:
                return obj, None, target
            if isinstance(produced, tuple) and len(produced) == 2 \
                    and callable(produced[0]):
                fn, args = produced
                return fn, tuple(args), target
            return obj, None, target
        return obj, None, target
    # artifact path: directory containing *.pdmodel, or the prefix itself
    prefix = target
    if os.path.isdir(target):
        models = [f for f in sorted(os.listdir(target))
                  if f.endswith(".pdmodel")]
        if not models:
            raise SystemExit(f"no .pdmodel artifact under {target}")
        prefix = os.path.join(target, models[0][: -len(".pdmodel")])
    elif target.endswith(".pdmodel"):
        prefix = target[: -len(".pdmodel")]
    from .. import jit
    return jit.load(prefix), None, prefix


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="jaxpr-level program linter")
    ap.add_argument("target", nargs="?",
                    help="module:fn or jit.save artifact prefix/dir")
    ap.add_argument("--input", action="append", default=[],
                    metavar="DTYPE:D0,D1",
                    help="input aval, e.g. f32:8,16 (repeatable)")
    ap.add_argument("--donate", default="",
                    help="comma-separated donated argnums")
    ap.add_argument("--passes", default="",
                    help="comma-separated pass ids (default: all)")
    ap.add_argument("--selflint", action="store_true",
                    help="run the AST self-lint over paddle_tpu/ instead")
    args = ap.parse_args(argv)

    if args.selflint:
        from .selflint import lint_repo
        findings = lint_repo()
        for f in findings:
            print(f)
        print(f"self-lint: {len(findings)} finding(s)")
        return 1 if findings else 0

    if not args.target:
        ap.error("a target (or --selflint) is required")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from . import analyze
    fn, fn_args, name = _resolve(args.target)
    if fn_args is None:
        fn_args = tuple(_parse_input(s) for s in args.input)
    donate = tuple(int(x) for x in args.donate.split(",") if x)
    passes = [p for p in args.passes.split(",") if p] or None
    report = analyze(fn, *fn_args, donate_argnums=donate, passes=passes,
                     name=name)
    print(report.table())
    return 0 if report.ok() else 1


if __name__ == "__main__":
    sys.exit(main())
