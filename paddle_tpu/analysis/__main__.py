"""CLI: ``python -m paddle_tpu.analysis <target>``.

Targets:

* ``module:attr`` — import ``module`` and resolve ``attr``. If calling
  ``attr()`` with no arguments returns ``(fn, example_args)`` (the
  ``__graft_entry__.entry`` convention) that pair is analyzed; otherwise
  ``attr`` itself is the target and ``--input`` specs supply the avals.
* a ``jit.save`` artifact prefix or directory (``m`` for ``m.pdmodel``)
  — loaded and analyzed from its saved input specs.

Options: ``--input dtype:d0,d1,...`` (repeatable), ``--donate 0,1``,
``--passes a,b``, ``--selflint`` (lint paddle_tpu's own source instead),
``--budget BYTES`` (fit-before-compile gate: fail when the target's
donation-aware ``static_peak_bytes`` exceeds the budget, naming the
fattest program point), ``--json`` (machine-readable findings on stdout
— one object with ``target``/``ok``/``static_peak_bytes``/``budget`` and
per-finding ``pass``/``severity``/``message``/``source``/``primitive``/
``data`` bytes fields — the CI-consumable form).

Exit-code contract (stable, CI-facing): **0** clean — no error-severity
findings and the static peak fits any ``--budget``; **1** error-severity
findings, any self-lint finding, or static peak over ``--budget``;
**2** usage errors (argparse). ``--json`` never changes the exit code,
only the output format.
"""
from __future__ import annotations

import argparse
import importlib
import json
import os
import sys


_DTYPES = {"f32": "float32", "f64": "float64", "bf16": "bfloat16",
           "f16": "float16", "i32": "int32", "i64": "int64",
           "i8": "int8", "u8": "uint8", "bool": "bool"}


def _parse_input(spec: str):
    import jax
    import numpy as np
    if ":" in spec:
        dtype, _, dims = spec.partition(":")
    else:
        dtype, dims = "float32", spec
    dtype = _DTYPES.get(dtype, dtype)
    shape = tuple(int(d) for d in dims.replace("x", ",").split(",") if d)
    return jax.ShapeDtypeStruct(shape, np.dtype(dtype))


def _resolve(target: str):
    """-> (fn_or_obj, args or None, display name)."""
    if ":" in target and not os.path.exists(target.split(":")[0]):
        mod_name, _, attr = target.rpartition(":")
        sys.path.insert(0, os.getcwd())
        obj = getattr(importlib.import_module(mod_name), attr)
        if callable(obj):
            try:
                produced = obj()
            except TypeError:
                return obj, None, target
            if isinstance(produced, tuple) and len(produced) == 2 \
                    and callable(produced[0]):
                fn, args = produced
                return fn, tuple(args), target
            return obj, None, target
        return obj, None, target
    # artifact path: directory containing *.pdmodel, or the prefix itself
    prefix = target
    if os.path.isdir(target):
        models = [f for f in sorted(os.listdir(target))
                  if f.endswith(".pdmodel")]
        if not models:
            raise SystemExit(f"no .pdmodel artifact under {target}")
        prefix = os.path.join(target, models[0][: -len(".pdmodel")])
    elif target.endswith(".pdmodel"):
        prefix = target[: -len(".pdmodel")]
    from .. import jit
    return jit.load(prefix), None, prefix


def _report_peak_bytes(report):
    """static_peak_bytes from the report's static-memory finding, or
    None when the trace failed (never a fake number)."""
    for f in report.findings:
        if f.pass_id == "static-memory" and f.data:
            return f.data.get("static_peak_bytes")
    return None


def _report_json(report, budget, fits) -> str:
    return json.dumps({
        "target": report.target,
        "ok": report.ok() and fits is not False,
        "n_eqns": report.n_eqns,
        "passes_run": report.passes_run,
        "static_peak_bytes": _report_peak_bytes(report),
        "budget_bytes": budget,
        "fits_budget": fits,
        "findings": [{
            "pass": f.pass_id, "severity": f.severity,
            "message": f.message, "source": f.source,
            "primitive": f.primitive, "fix_hint": f.fix_hint,
            "data": f.data,
        } for f in report.findings],
    })


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="jaxpr-level program linter")
    ap.add_argument("target", nargs="?",
                    help="module:fn or jit.save artifact prefix/dir")
    ap.add_argument("--input", action="append", default=[],
                    metavar="DTYPE:D0,D1",
                    help="input aval, e.g. f32:8,16 (repeatable)")
    ap.add_argument("--donate", default="",
                    help="comma-separated donated argnums")
    ap.add_argument("--passes", default="",
                    help="comma-separated pass ids (default: all)")
    ap.add_argument("--budget", type=int, default=None, metavar="BYTES",
                    help="HBM budget: exit 1 when the target's static "
                         "peak bytes (donation-aware liveness) exceed it")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings JSON on stdout "
                         "(exit codes unchanged)")
    ap.add_argument("--selflint", action="store_true",
                    help="run the AST self-lint over paddle_tpu/ instead")
    args = ap.parse_args(argv)

    if args.selflint:
        from .selflint import lint_repo
        findings = lint_repo()
        if args.json:
            print(json.dumps({"selflint": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "message": f.message} for f in findings]}))
        else:
            for f in findings:
                print(f)
            print(f"self-lint: {len(findings)} finding(s)")
        return 1 if findings else 0

    if not args.target:
        ap.error("a target (or --selflint) is required")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from . import analyze
    fn, fn_args, name = _resolve(args.target)
    if fn_args is None:
        fn_args = tuple(_parse_input(s) for s in args.input)
    donate = tuple(int(x) for x in args.donate.split(",") if x)
    passes = [p for p in args.passes.split(",") if p] or None
    report = analyze(fn, *fn_args, donate_argnums=donate, passes=passes,
                     name=name)

    peak = _report_peak_bytes(report)
    fits = None
    if args.budget is not None:
        # the fit-before-compile gate: an untraceable target (peak is
        # None) cannot certify fit, so it fails the gate honestly
        fits = peak is not None and peak <= args.budget

    if args.json:
        print(_report_json(report, args.budget, fits))
    else:
        print(report.table())
        if fits is False:
            print(f"budget: static peak "
                  f"{'unknown (trace failed)' if peak is None else f'{peak:,} B'} "
                  f"exceeds --budget {args.budget:,} B")
        elif fits:
            print(f"budget: static peak {peak:,} B fits "
                  f"--budget {args.budget:,} B")
    return 0 if (report.ok() and fits is not False) else 1


if __name__ == "__main__":
    sys.exit(main())
