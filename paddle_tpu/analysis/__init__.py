"""``paddle_tpu.analysis`` — jaxpr-level program linter.

The TPU-native analog of the reference's IR-pass layer
(paddle/fluid/framework/ir): instead of pattern passes over a
ProgramDesc graph, :func:`analyze` closed-jaxpr-traces a callable (or
replays a captured ``paddle.static`` Program) without compiling it and
runs registered passes over the trace. Six ship built-in:

=================  ========================================================
host-sync          pure_callback/io_callback eqns, and ``.numpy()``/
                   ``float()``/``bool()`` concretization inside traced fns
                   diagnosed with the offending source line
donation-safety    donated args whose buffers are structurally unsafe
                   (no rebind target / double alias) — the standing guard
                   for the PR-2 donated train step
dead-grad          params with structurally-zero cotangents still in the
                   trainable set (the optimizer decays them anyway)
dtype-hygiene      f64 leaks; silent bf16->f32 upcasts in autocast regions
collective-pairing a reduce-scatter whose axis/dimension/tiling has no
                   matching closing all-gather (the ZeRO sharded-update
                   loop left open or permuted)
recompile-churn    why retraces fired (shape/dtype/static-arg/frozen-set),
                   from the ``dispatch/retrace_cause`` trace probe
static-memory      donation-aware liveness scan (:mod:`.liveness`):
                   ``static_peak_bytes`` + the fattest program point,
                   before any compile
donation-miss      large invars that die early but are not donated, with
                   the peak-bytes reduction donating would buy
sharding-consistency  inside shard_map: collective axes must exist on the
                   mesh, reduce_scatter/all_gather pairing must close,
                   large fully-replicated operands priced per device
=================  ========================================================

Three integration surfaces: ``Model.fit(..., analyze='warn'|'error')``
(default from ``FLAGS_static_analysis``), an ``Executor.run`` pre-flight
over captured Programs, and the CLI
``python -m paddle_tpu.analysis <module:fn | saved-artifact-prefix>``.
:mod:`.selflint` additionally lints ``paddle_tpu``'s own source (AST
rules) and runs as a tier-1 test.
"""
from __future__ import annotations

from .core import (AnalysisContext, AnalysisError, Finding, Report,  # noqa
                   all_passes, analyze, iter_eqns, register_pass)
from . import passes as _passes  # noqa: F401  (registers the built-ins)
from . import liveness  # noqa: F401
from .liveness import (LivenessReport, callable_liveness,  # noqa: F401
                       jaxpr_liveness)
from .selflint import lint_repo, lint_source  # noqa: F401

__all__ = ["analyze", "analyze_model", "apply_mode", "Finding", "Report",
           "AnalysisError", "AnalysisContext", "register_pass",
           "all_passes", "lint_repo", "lint_source", "liveness",
           "LivenessReport", "callable_liveness", "jaxpr_liveness"]


def flag_mode() -> str:
    """``FLAGS_static_analysis`` normalized to 'off'|'warn'|'error'.
    Lenient on boolean-style env values (the convention of the
    neighboring FLAGS_compile_cache=1 knobs): truthy strings mean
    'warn', anything unrecognized means 'off' — a misconfigured env var
    must degrade to un-linted, not crash every fit()."""
    from ..framework.flags import flag_value
    s = str(flag_value("FLAGS_static_analysis")).strip().lower()
    if s in ("warn", "warning", "1", "true", "on", "yes"):
        return "warn"
    if s in ("error", "strict"):
        return "error"
    return "off"


def apply_mode(report, mode, label):
    """The shared warn/error policy of the integration surfaces
    (``Model.fit(analyze=...)``, ``Executor.run`` pre-flight): emit the
    findings table as a UserWarning when anything warning-or-worse was
    found (info-only reports stay silent — they live in the report and
    the counters), and raise :class:`AnalysisError` in ``'error'`` mode
    when error-severity findings exist. Returns ``report``."""
    if report is None:
        return None
    if report.warnings() or report.errors():
        import warnings
        warnings.warn(f"static analysis of {label}:\n" + report.table(),
                      UserWarning)
    if mode == "error" and not report.ok():
        raise AnalysisError(report)
    return report


def analyze_model(model, inputs, labels=None, passes=None, name=None):
    """Analyze a prepared hapi ``Model``'s REAL donated train step.

    Traces ``model._train_step_fn`` (donation contract auto-read from
    the pjit eqn / declared argnums) on one example batch, builds the
    grad jaxpr of the trainable-params loss for the dead-grad pass, and
    runs the full pipeline. Nothing executes on device — tracing only.
    """
    import jax
    import jax.numpy as jnp

    from ..hapi.model import _as_arrays

    if model._optimizer is None or model._loss is None:
        raise ValueError(
            "analyze_model needs a prepared Model: call "
            "model.prepare(optimizer, loss) first")
    ins = _as_arrays(inputs)
    lbs = _as_arrays(labels) if labels is not None else []
    model._ensure_train_built()

    loss_fn, train_p = model._analysis_loss_fn(ins, lbs)
    grad = None
    if train_p:
        from .core import _concretization_errors
        try:
            grad_jaxpr = jax.make_jaxpr(jax.grad(loss_fn))(train_p)
            names = sorted(train_p)  # dict pytree flatten order
            grad = {"jaxpr": grad_jaxpr, "names": names,
                    "trainable": set(names)}
        except _concretization_errors():
            # the forward itself concretizes a tracer — the step trace
            # below hits the same line and the host-sync pass reports it
            # with source provenance; grad analysis is moot until fixed
            grad = None

    key = jax.random.key(0)
    lr = jnp.asarray(model._optimizer.get_lr(), jnp.float32)
    # with the numerics audit fused into the step (fit(numerics=...)),
    # the signature grows a traced inject scalar before the static
    # n_inputs — mirror the dispatch path so the trace matches the
    # program that actually runs
    if getattr(model, "_audit_enabled", False):
        step_args = (model._params, model._opt_state, model._buffers,
                     key, lr, jnp.float32(1.0), len(ins), *ins, *lbs)
        static_argnums = (6,)
    else:
        step_args = (model._params, model._opt_state, model._buffers,
                     key, lr, len(ins), *ins, *lbs)
        static_argnums = (5,)
    return analyze(model._train_step_fn, *step_args,
                   donate_argnums=(0, 1, 2), static_argnums=static_argnums,
                   passes=passes, grad=grad,
                   name=name or
                   f"Model({type(model.network).__name__}).train_step")
