"""Donation-aware static liveness analysis over jaxprs (ISSUE 18).

The reference stack answers "will it fit?" only AFTER paying a compile
(`memory_optimize` / the inplace pass run on the fully built
ProgramDesc) or after an OOM postmortem. Here the question is answered
on the jaxpr we already trace for the PR-3 analysis passes: a linear
liveness scan over program order computes, at every equation, the bytes
that must be resident — pinned inputs, donated inputs still awaiting
their last use, intermediates between production and last consumption,
and outputs from production to program end — and reports the maximum as
``static_peak_bytes`` together with a top-k timeline of the fattest
program points, each blamed to user source via the PR-3
``eqn_source`` machinery.

The model (documented so the cross-check tolerance is auditable):

* **non-donated invars and constvars are pinned** for the whole
  program — jit may not overwrite caller buffers;
* **donated invars die at their last use** — XLA may then reuse the
  buffer (an invar that is also an output stays pinned);
* **intermediates live** from the eqn that produces them to their last
  consuming eqn; results unused later are charged at their producing
  point only (they materialize, then free);
* **outputs are pinned** from their producing eqn to program end;
* **sub-jaxprs** (pjit / shard_map / scan / while / cond /
  custom_vjp) are walked recursively: the inner program's peak is
  charged at the calling eqn with the operand/result bytes already
  counted in the outer frame discounted, and exclusive branches
  (cond) contribute their max, not their sum. ``shard_map`` bodies
  carry PER-DEVICE avals, so recursion prices the sharded interior
  correctly while the outer (global-shape) operands remain the
  replicated upper bound.

This is a NO-FUSION upper-bound estimator: XLA's fusion and buffer
aliasing can only shrink the real footprint below it, while the real
peak can exceed only by workspace XLA adds (convolution scratch,
collective staging). ``CROSSCHECK_RTOL`` documents the bracket the
dry-run asserts against ``memory_analysis()`` where the backend
reports figures; where it does not, fields stay ``None`` — never a
fake number.

Everything here is host arithmetic over avals. The module must never
compile or touch the device — enforced by the ``analysis-no-device``
self-lint rule.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence

import numpy as np

from .core import _donated_invars, _trace_callable, eqn_source

__all__ = [
    "aval_bytes", "jaxpr_liveness", "callable_liveness",
    "donation_misses", "crosscheck", "PeakPoint", "LivenessReport",
    "CROSSCHECK_RTOL", "DONATION_MISS_MIN_BYTES",
]

# The documented cross-check bracket (see module docstring): with
# xla = argument + temp + output - alias (memory_analysis()'s resident
# footprint, donated aliases counted once), the dry-run asserts
#   xla / CROSSCHECK_RTOL  <=  static_peak_bytes
#   static_peak_bytes      <=  xla * CROSSCHECK_RTOL
# 4x absorbs fusion on the low side (XLA eliding intermediates the
# no-fusion model charges) and padding/workspace on the high side.
CROSSCHECK_RTOL = 4.0

# donation-miss pass floor: invars below this are not worth a finding
# (donating a few KiB buys nothing on any real device).
DONATION_MISS_MIN_BYTES = 1 << 20


def aval_bytes(aval) -> int:
    """Bytes one materialized value of ``aval`` occupies; 0 for
    tokens/refs/symbolic shapes (best-effort, never raises)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    try:
        for d in shape:
            n *= int(d)
        return n * np.dtype(dtype).itemsize
    except Exception:
        return 0


@dataclass
class PeakPoint:
    """One program point of the liveness timeline."""

    index: int                  # position in traversal order
    primitive: str
    live_bytes: int
    source: Optional[str] = None
    depth: int = 0              # sub-jaxpr nesting depth

    def as_dict(self) -> dict:
        return {"index": self.index, "primitive": self.primitive,
                "live_bytes": self.live_bytes, "source": self.source,
                "depth": self.depth}


@dataclass
class LivenessReport:
    """Result of one liveness scan."""

    static_peak_bytes: int
    peak: Optional[PeakPoint]
    timeline: List[PeakPoint] = field(default_factory=list)  # top-k, fattest first
    arg_bytes: int = 0          # all top-level invars
    donated_bytes: int = 0      # donated subset of arg_bytes
    const_bytes: int = 0
    out_bytes: int = 0
    n_points: int = 0

    def as_dict(self) -> dict:
        return {
            "static_peak_bytes": self.static_peak_bytes,
            "peak": self.peak.as_dict() if self.peak else None,
            "timeline": [p.as_dict() for p in self.timeline],
            "arg_bytes": self.arg_bytes,
            "donated_bytes": self.donated_bytes,
            "const_bytes": self.const_bytes,
            "out_bytes": self.out_bytes,
            "n_points": self.n_points,
        }

    def table(self) -> str:
        lines = [f"static peak {self.static_peak_bytes:,} B over "
                 f"{self.n_points} program points "
                 f"(args {self.arg_bytes:,} B, {self.donated_bytes:,} B "
                 f"donated; outputs {self.out_bytes:,} B)"]
        for p in self.timeline:
            lines.append(f"  {p.live_bytes:>14,} B  {p.primitive:<20} "
                         f"{p.source or '-'}")
        return "\n".join(lines)


def _is_literal(v) -> bool:
    return hasattr(v, "val")


def _walk(jaxpr, donated: Optional[Sequence[bool]], base: int,
          points: List[PeakPoint], depth: int) -> int:
    """Linear liveness scan over one (raw) jaxpr level. ``base`` is the
    byte load pinned by enclosing frames; returns the base-inclusive
    peak of this level and everything below it. Appends a PeakPoint
    per eqn (inner levels append their own)."""
    eqns = jaxpr.eqns
    n = len(eqns)

    last_use = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if not _is_literal(v):
                last_use[v] = i
    for v in jaxpr.outvars:
        if not _is_literal(v):
            last_use[v] = n              # pinned through program end

    live = {}                            # var -> bytes, freeable later
    pinned = 0
    for v in jaxpr.constvars:
        pinned += aval_bytes(v.aval)
    for k, v in enumerate(jaxpr.invars):
        b = aval_bytes(v.aval)
        lu = last_use.get(v)
        if donated is not None and k < len(donated) and donated[k] \
                and lu is not None and lu < n:
            live[v] = b                  # donated: frees after last use
        elif donated is not None and k < len(donated) and donated[k] \
                and lu is None:
            pass                         # dead donation: freeable at entry
        else:
            pinned += b                  # caller's buffer, pinned
    cur = pinned + sum(live.values())
    peak = base + cur
    if depth == 0:
        points.append(PeakPoint(len(points), "<args>", peak, None, depth))

    for i, eqn in enumerate(eqns):
        out_total = sum(aval_bytes(v.aval) for v in eqn.outvars
                        if not _is_literal(v))
        at_point = base + cur + out_total
        subs = [x for x in _sub_jaxprs_raw(eqn)]
        inner_peak = 0
        if subs:
            don_inner = eqn.params.get("donated_invars") \
                if len(subs) == 1 else None
            for sub in subs:
                io = sum(aval_bytes(v.aval) for v in sub.invars) + \
                     sum(aval_bytes(v.aval) for v in sub.outvars
                         if not _is_literal(v))
                inner_base = max(0, at_point - io)
                p = _walk(sub, don_inner, inner_base, points, depth + 1)
                inner_peak = max(inner_peak, p)   # exclusive branches: max
        points.append(PeakPoint(len(points), eqn.primitive.name,
                                at_point, eqn_source(eqn), depth))
        peak = max(peak, at_point, inner_peak)
        # free operands whose last use is here
        for v in eqn.invars:
            if not _is_literal(v) and v in live and last_use.get(v) == i:
                cur -= live.pop(v)
        # results used later become live; results never read again were
        # charged transiently at this point only
        for v in eqn.outvars:
            if _is_literal(v):
                continue
            lu = last_use.get(v)
            if lu is not None and lu > i and v not in live:
                b = aval_bytes(v.aval)
                live[v] = b
                cur += b
    return peak


def _sub_jaxprs_raw(eqn):
    """Raw sub-jaxprs of one eqn (ClosedJaxpr unwrapped) — the liveness
    twin of core._sub_jaxprs, kept here so the walk can pair each sub
    with the eqn's donation param."""
    for v in eqn.params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for x in vals:
            if hasattr(x, "jaxpr") and hasattr(x.jaxpr, "eqns"):
                yield x.jaxpr
            elif hasattr(x, "eqns"):
                yield x


def jaxpr_liveness(closed, donated_invars: Optional[Sequence[bool]] = None,
                   top_k: int = 8) -> LivenessReport:
    """Liveness scan over a ClosedJaxpr (or raw Jaxpr)."""
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    points: List[PeakPoint] = []
    peak_bytes = _walk(jaxpr, donated_invars, 0, points, 0)

    arg_bytes = sum(aval_bytes(v.aval) for v in jaxpr.invars)
    donated_bytes = 0
    if donated_invars is not None:
        donated_bytes = sum(
            aval_bytes(v.aval)
            for v, d in zip(jaxpr.invars, donated_invars) if d)
    const_bytes = sum(aval_bytes(v.aval) for v in jaxpr.constvars)
    out_bytes = sum(aval_bytes(v.aval) for v in jaxpr.outvars
                    if not _is_literal(v))

    peak_pt = max(points, key=lambda p: p.live_bytes) if points else None
    timeline = sorted(points, key=lambda p: -p.live_bytes)[:max(0, top_k)]
    return LivenessReport(
        static_peak_bytes=peak_bytes, peak=peak_pt, timeline=timeline,
        arg_bytes=arg_bytes, donated_bytes=donated_bytes,
        const_bytes=const_bytes, out_bytes=out_bytes,
        n_points=len(points))


def callable_liveness(fn, *args, donate_argnums=(), static_argnums=(),
                      top_k: int = 8) -> LivenessReport:
    """Trace ``fn(*args)`` (PR-3 Tensor-aware tracing, no compile, no
    device work) and run the liveness scan. Donation comes from the
    explicit ``donate_argnums`` or, for an already-jitted fn, from its
    pjit eqn's donation contract."""
    closed, ranges = _trace_callable(fn, args, static_argnums)
    donated = _donated_invars(closed, tuple(donate_argnums), ranges)
    return jaxpr_liveness(closed, donated, top_k=top_k)


def donation_misses(closed, donated_invars: Optional[Sequence[bool]] = None,
                    min_bytes: int = DONATION_MISS_MIN_BYTES,
                    max_candidates: int = 8) -> List[dict]:
    """Large non-donated invars that die before program end, each with
    the ``static_peak_bytes`` reduction donating it would buy (a
    liveness re-scan with the invar marked donated — honest, not a
    heuristic). Entries with zero saving are dropped: donating an
    input whose lifetime spans the peak buys nothing in this model.

    Also returns ``kind='dead'`` entries for donated invars the program
    never reads (the dead-donation contract violation this analysis
    supersedes from the old boolean check)."""
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    n = len(jaxpr.eqns)
    base = jaxpr_liveness(closed, donated_invars, top_k=1)

    last_use = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not _is_literal(v):
                last_use[v] = i
    outset = {id(v) for v in jaxpr.outvars if not _is_literal(v)}

    donated = list(donated_invars) if donated_invars is not None else \
        [False] * len(jaxpr.invars)
    out: List[dict] = []
    candidates = []
    for k, v in enumerate(jaxpr.invars):
        is_donated = k < len(donated) and donated[k]
        used = v in last_use
        if is_donated and not used:
            out.append({"kind": "dead", "argnum": k,
                        "bytes": aval_bytes(v.aval), "saving_bytes": 0,
                        "last_use_source": None})
            continue
        if is_donated or id(v) in outset:
            continue                     # donated already / returned
        b = aval_bytes(v.aval)
        if b < min_bytes:
            continue
        candidates.append((b, k, v))
    candidates.sort(key=lambda t: -t[0])
    for b, k, v in candidates[:max(0, max_candidates)]:
        trial = list(donated) + [False] * (len(jaxpr.invars) - len(donated))
        trial[k] = True
        saving = base.static_peak_bytes - \
            jaxpr_liveness(closed, trial, top_k=0).static_peak_bytes
        if saving <= 0:
            continue
        lu = last_use.get(v)
        src = eqn_source(jaxpr.eqns[lu]) if lu is not None else None
        out.append({"kind": "miss", "argnum": k, "bytes": b,
                    "saving_bytes": int(saving), "last_use_source": src})
    return out


def crosscheck(static_peak_bytes: Optional[int],
               argument_bytes: Optional[int],
               output_bytes: Optional[int],
               temp_bytes: Optional[int],
               alias_bytes: Optional[int] = None,
               rtol: float = CROSSCHECK_RTOL) -> Optional[dict]:
    """Compare the static estimate against XLA ``memory_analysis()``
    figures. Returns ``None`` when the backend reported nothing (the
    honesty contract: no fake numbers) — otherwise a dict with the XLA
    resident footprint (argument + temp + output, donated aliases
    counted once), the ratio, and whether it sits inside the documented
    ``CROSSCHECK_RTOL`` bracket."""
    if static_peak_bytes is None or temp_bytes is None \
            or output_bytes is None:
        return None
    xla = int(temp_bytes) + int(output_bytes) + int(argument_bytes or 0) \
        - int(alias_bytes or 0)
    if xla <= 0 or static_peak_bytes <= 0:
        return None
    ratio = float(static_peak_bytes) / float(xla)
    return {"xla_bytes": xla, "static_peak_bytes": int(static_peak_bytes),
            "ratio": ratio, "rtol": rtol,
            "ok": (1.0 / rtol) <= ratio <= rtol}
