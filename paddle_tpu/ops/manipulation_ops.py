"""Shape/layout manipulation, indexing, search & sort op implementations.

Analog of phi's manipulation family (/root/reference/paddle/phi/kernels/
reshape_kernel.h, concat_kernel.h, gather_kernel.h, scatter_kernel.h,
top_k_kernel.h, ...). Gather/scatter map to XLA gather/scatter which TPU
executes natively; dynamic-shape ops (unique, nonzero, masked_select) expose
a ``size``-bounded variant where needed for jit-ability.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


@register_op("reshape")
def _reshape(x, shape):
    # reference semantics: a 0 in the target copies the input dim at that
    # position (phi ReshapeInferMeta)
    shape = tuple(x.shape[i] if d == 0 and i < x.ndim else d
                  for i, d in enumerate(shape))
    return jnp.reshape(x, shape)


@register_op("transpose")
def _transpose(x, perm):
    return jnp.transpose(x, tuple(perm))


@register_op("concat")
def _concat(xs, axis=0):
    return jnp.concatenate(xs, axis=int(axis))


@register_op("stack")
def _stack(xs, axis=0):
    return jnp.stack(xs, axis=int(axis))


@register_op("unstack")
def _unstack(x, axis=0, num=None):
    n = num if num is not None else x.shape[axis]
    return tuple(jnp.squeeze(s, axis=axis)
                 for s in jnp.split(x, n, axis=axis))


@register_op("split")
def _split(x, num_or_sections, axis=0):
    axis = int(axis)
    if isinstance(num_or_sections, int):
        return tuple(jnp.split(x, num_or_sections, axis=axis))
    sections = list(num_or_sections)
    # allow one -1 entry like the reference (phi SplitInferMeta)
    if -1 in sections:
        known = sum(s for s in sections if s != -1)
        sections[sections.index(-1)] = x.shape[axis] - known
    idx = []
    acc = 0
    for s in sections[:-1]:
        acc += s
        idx.append(acc)
    return tuple(jnp.split(x, idx, axis=axis))


@register_op("squeeze")
def _squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, (list, tuple)):
        ax = tuple(a for a in axis if x.shape[a] == 1)
        return jnp.squeeze(x, axis=ax) if ax else x
    if x.shape[axis] != 1:
        return x
    return jnp.squeeze(x, axis=axis)


@register_op("unsqueeze")
def _unsqueeze(x, axis):
    if isinstance(axis, (list, tuple)):
        out = x
        for a in sorted(axis):
            out = jnp.expand_dims(out, a)
        return out
    return jnp.expand_dims(x, int(axis))


@register_op("flatten")
def _flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return x.reshape((1,))
    s = start_axis % nd
    e = stop_axis % nd
    shape = x.shape[:s] + (-1,) + x.shape[e + 1:]
    return x.reshape(shape)


@register_op("gather")
def _gather(x, index, axis=0):
    idx = index
    if idx.ndim == 0:
        idx = idx[None]
    return jnp.take(x, idx, axis=int(axis))


@register_op("gather_nd")
def _gather_nd(x, index):
    # reference: phi/kernels/gather_nd_kernel.h — index[..., k] indexes the
    # first k dims of x.
    k = index.shape[-1]
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@register_op("scatter")
def _scatter(x, index, updates, overwrite=True):
    idx = index.reshape(-1)
    if overwrite:
        return x.at[idx].set(updates)
    # paddle overwrite=False: zero the rows then accumulate
    zeroed = x.at[idx].set(jnp.zeros_like(updates))
    return zeroed.at[idx].add(updates)


@register_op("scatter_nd_add")
def _scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


@register_op("index_select")
def _index_select(x, index, axis=0):
    return jnp.take(x, index, axis=int(axis))


@register_op("index_sample")
def _index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


@register_op("take_along_axis")
def _take_along_axis(x, indices, axis):
    return jnp.take_along_axis(x, indices, axis=int(axis))


@register_op("put_along_axis")
def _put_along_axis(x, indices, values, axis, reduce="assign"):
    if reduce == "add":
        return jnp.put_along_axis(x, indices, values, axis=int(axis),
                                  inplace=False, mode="drop") \
            if hasattr(jnp, "put_along_axis") else \
            _pa_fallback(x, indices, values, axis, "add")
    return _pa_fallback(x, indices, values, axis, reduce)


def _pa_fallback(x, indices, values, axis, reduce):
    axis = int(axis)
    dims = tuple(
        jnp.broadcast_to(
            jnp.arange(x.shape[d]).reshape(
                tuple(-1 if i == d else 1 for i in range(x.ndim))),
            indices.shape)
        if d != axis else indices
        for d in range(x.ndim))
    v = jnp.broadcast_to(values, indices.shape).astype(x.dtype)
    if reduce == "add":
        return x.at[dims].add(v)
    if reduce == "multiply" or reduce == "mul":
        return x.at[dims].multiply(v)
    return x.at[dims].set(v)


@register_op("where")
def _where(cond, x, y):
    return jnp.where(cond, x, y)


@register_op("nonzero", nondiff=True, jit=False)
def _nonzero(x, as_tuple=False):
    nz = jnp.nonzero(x)
    if as_tuple:
        return tuple(a[:, None].astype(jnp.int64) for a in nz)
    return jnp.stack(nz, axis=1).astype(jnp.int64)


@register_op("masked_select", nondiff=True, jit=False)
def _masked_select(x, mask):
    return x[mask]


def _leading_mask(mask, ndim):
    """Expand a leading-dims boolean mask for numpy-style broadcasting:
    x[mask] aligns mask with x's LEADING axes, while jnp.where aligns
    trailing — so pad the mask with trailing singleton dims."""
    return mask.reshape(mask.shape + (1,) * (ndim - mask.ndim))


@register_op("masked_fill")
def _masked_fill(x, mask, value):
    return jnp.where(_leading_mask(mask, x.ndim),
                     jnp.asarray(value, x.dtype), x)


@register_op("tile")
def _tile(x, repeat_times):
    return jnp.tile(x, tuple(repeat_times))


@register_op("expand")
def _expand(x, shape):
    shape = tuple(s if s != -1 else x.shape[i - (len(shape) - x.ndim)]
                  for i, s in enumerate(shape))
    return jnp.broadcast_to(x, shape)


@register_op("broadcast_to")
def _broadcast_to(x, shape):
    return jnp.broadcast_to(x, tuple(shape))


@register_op("expand_as")
def _expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


@register_op("flip")
def _flip(x, axis):
    if isinstance(axis, int):
        axis = [axis]
    return jnp.flip(x, axis=tuple(axis))


@register_op("roll")
def _roll(x, shifts, axis=None):
    return jnp.roll(x, shifts,
                    axis=tuple(axis) if isinstance(axis, (list, tuple))
                    else axis)


@register_op("rot90")
def _rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@register_op("pad")
def _pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    # ``pad`` is a flat list in paddle order.
    nd = x.ndim
    if len(pad) == 2 * nd:
        widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # Partial spec applies to trailing spatial dims, LAST dim first:
        # [left, right, top, bottom] pads W by (left,right) then H — the
        # convention of the reference's nn/functional pad.
        n_spatial = len(pad) // 2
        widths = [(0, 0)] * nd
        if data_format.startswith("N") and data_format[1] == "C":
            start = 2
        elif data_format.startswith("N"):
            start = 1
        else:
            start = nd - n_spatial
        for i in range(n_spatial):
            widths[start + n_spatial - 1 - i] = (pad[2 * i], pad[2 * i + 1])
    mode_map = {"constant": "constant", "reflect": "reflect",
                "replicate": "edge", "circular": "wrap"}
    if mode == "constant":
        return jnp.pad(x, widths, mode="constant", constant_values=value)
    return jnp.pad(x, widths, mode=mode_map[mode])


@register_op("chunk")
def _chunk(x, chunks, axis=0):
    return tuple(jnp.array_split(x, int(chunks), axis=int(axis)))


@register_op("unique", nondiff=True, jit=False)
def _unique(x, return_index=False, return_inverse=False,
            return_counts=False, axis=None):
    res = jnp.unique(x, return_index=return_index,
                     return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    return res


@register_op("unique_consecutive", nondiff=True, jit=False)
def _unique_consecutive(x, return_inverse=False, return_counts=False):
    import numpy as np
    a = np.asarray(x)
    mask = np.concatenate([[True], a[1:] != a[:-1]]) if a.size else \
        np.ones((0,), bool)
    out = [jnp.asarray(a[mask])]
    if return_inverse:
        out.append(jnp.asarray(np.cumsum(mask) - 1))
    if return_counts:
        idx = np.flatnonzero(mask)
        out.append(jnp.asarray(np.diff(np.append(idx, a.size))))
    return out[0] if len(out) == 1 else tuple(out)


@register_op("sort")
def _sort(x, axis=-1, descending=False, stable=True):
    r = jnp.sort(x, axis=int(axis), stable=stable)
    return jnp.flip(r, axis=int(axis)) if descending else r


@register_op("argsort", nondiff=True)
def _argsort(x, axis=-1, descending=False, stable=True):
    r = jnp.argsort(x, axis=int(axis), stable=stable)
    if descending:
        r = jnp.flip(r, axis=int(axis))
    return r.astype(jnp.int64)


@register_op("topk")
def _topk(x, k, axis=-1, largest=True, sorted=True):
    axis = int(axis) % x.ndim
    xm = jnp.moveaxis(x, axis, -1)
    if largest:
        vals, idx = lax.top_k(xm, int(k))
    else:
        vals, idx = lax.top_k(-xm, int(k))
        vals = -vals
    return (jnp.moveaxis(vals, -1, axis),
            jnp.moveaxis(idx, -1, axis).astype(jnp.int64))


@register_op("searchsorted", nondiff=True)
def _searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    if sorted_sequence.ndim == 1:
        r = jnp.searchsorted(sorted_sequence, values, side=side)
    else:
        r = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))(
            sorted_sequence.reshape(-1, sorted_sequence.shape[-1]),
            values.reshape(-1, values.shape[-1]))
        r = r.reshape(values.shape)
    return r.astype(jnp.int32 if out_int32 else jnp.int64)


@register_op("bucketize", nondiff=True)
def _bucketize(x, sorted_sequence, out_int32=False, right=False):
    side = "right" if right else "left"
    r = jnp.searchsorted(sorted_sequence, x, side=side)
    return r.astype(jnp.int32 if out_int32 else jnp.int64)


@register_op("one_hot", nondiff=True)
def _one_hot(x, num_classes, dtype="float32"):
    return jax.nn.one_hot(x, int(num_classes), dtype=jnp.dtype(dtype))


@register_op("repeat_interleave")
def _repeat_interleave(x, repeats, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.repeat(x, repeats, axis=int(axis))


@register_op("getitem")
def _getitem(x, *index_arrays, index_spec=None):
    idx = _decode_index(index_spec, list(index_arrays))
    return x[idx]


@register_op("setitem")
def _setitem(x, value, *index_arrays, index_spec=None):
    idx = _decode_index(index_spec, list(index_arrays))
    return x.at[idx].set(jnp.asarray(value, x.dtype))


def _decode_index(spec, arrays):
    out = []
    for item in spec:
        kind = item[0]
        if kind == "slice":
            out.append(slice(item[1], item[2], item[3]))
        elif kind == "int":
            out.append(item[1])
        elif kind == "none":
            out.append(None)
        elif kind == "ellipsis":
            out.append(Ellipsis)
        elif kind == "array":
            out.append(arrays.pop(0))
        elif kind == "tuple":
            out.append(tuple(item[1]))
    return tuple(out)


@register_op("strided_slice")
def _strided_slice(x, axes, starts, ends, strides=None):
    idx = [slice(None)] * x.ndim
    strides = strides or [1] * len(axes)
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = slice(s, e, st)
    return x[tuple(idx)]


@register_op("slice")
def _slice(x, axes, starts, ends):
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        idx[a] = slice(s, e)
    return x[tuple(idx)]


@register_op("moveaxis")
def _moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


@register_op("swapaxes")
def _swapaxes(x, axis0, axis1):
    return jnp.swapaxes(x, axis0, axis1)


@register_op("as_strided")
def _as_strided(x, shape, stride, offset=0):
    flat = x.reshape(-1)
    idx = jnp.zeros(tuple(shape), dtype=jnp.int32) + offset
    for d, (s, st) in enumerate(zip(shape, stride)):
        r = jnp.arange(s) * st
        idx = idx + r.reshape(tuple(-1 if i == d else 1
                                    for i in range(len(shape))))
    return flat[idx]


@register_op("tensordot")
def _tensordot(x, y, axes=2):
    return jnp.tensordot(x, y, axes=axes)


@register_op("crop")
def _crop(x, shape, offsets):
    idx = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return x[idx]


@register_op("masked_fill_tensor")
def _masked_fill_tensor(x, mask, value):
    """numpy-style ``x[mask] = value``.

    * scalar value — broadcast fill of the selected region;
    * 1-D value of length k — assigned to the k True positions in row-major
      order (cumsum-gather keeps this jittable; a length mismatch is NOT
      detected under jit, matching the cost model of dynamic shapes on TPU).
    """
    value = value.astype(x.dtype)
    if value.size == 1:
        return jnp.where(_leading_mask(mask, x.ndim),
                         jnp.reshape(value, ()), x)
    if value.ndim == 1:
        flat_mask = jnp.broadcast_to(_leading_mask(mask, x.ndim),
                                     x.shape).ravel()
        pos = jnp.cumsum(flat_mask) - 1
        vals = value[jnp.clip(pos, 0, value.shape[0] - 1)]
        return jnp.where(flat_mask, vals, x.ravel()).reshape(x.shape)
    return jnp.where(_leading_mask(mask, x.ndim),
                     jnp.broadcast_to(value, x.shape), x)
