"""Op registry.

TPU-native analog of the reference's phi ``KernelFactory``
(/root/reference/paddle/phi/core/kernel_factory.h:261) and
``PD_REGISTER_KERNEL`` (phi/core/kernel_registry.h). Because XLA is the single
backend, the (Backend, Layout, DataType) key collapses: one registered impl
per op, expressed as a pure jax function. Backend selection, layout and fusion
are the compiler's job; Pallas variants register as *overrides* keyed by a
predicate (analogous to the reference's gpudnn/ kernels shadowing gpu/ ones).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import jax


class OpDef:
    __slots__ = ("name", "fn", "overrides", "nondiff", "jit")

    def __init__(self, name: str, fn: Callable, nondiff: bool, jit: bool):
        self.name = name
        self.fn = fn
        # list of (predicate(args, attrs) -> bool, fn) tried in reverse
        # registration order — the Pallas fast-path hook.
        self.overrides: List[Tuple[Callable, Callable]] = []
        self.nondiff = nondiff  # outputs never require grad (e.g. argmax)
        self.jit = jit

    def select(self, args, attrs) -> Callable:
        for pred, fn in reversed(self.overrides):
            try:
                if pred(args, attrs):
                    return fn
            except Exception:
                continue
        return self.fn


_OPS: Dict[str, OpDef] = {}


def register_op(name: str, nondiff: bool = False, jit: bool = True):
    """Decorator registering a pure-jax op implementation."""

    def deco(fn):
        if name in _OPS:
            raise KeyError(f"op {name!r} already registered")
        _OPS[name] = OpDef(name, fn, nondiff, jit)
        return fn

    return deco


def register_override(name: str, predicate: Callable):
    """Register a faster impl (e.g. a Pallas kernel) used when ``predicate``
    holds — the analog of a gpudnn/autotuned kernel shadowing the generic
    one."""

    def deco(fn):
        _OPS[name].overrides.append((predicate, fn))
        return fn

    return deco


def get_op(name: str) -> OpDef:
    try:
        return _OPS[name]
    except KeyError:
        raise NotImplementedError(f"op {name!r} is not registered") from None


def has_op(name: str) -> bool:
    return name in _OPS


def op_names():
    return sorted(_OPS)
