"""Linear algebra op implementations.

Analog of phi's matmul/blas family (/root/reference/paddle/phi/kernels/
matmul_kernel.h, funcs/blas/) and the linalg decompositions
(cholesky_kernel.h, svd_kernel.h, ...). Matmuls lower straight to the MXU via
``lax.dot_general``; on TPU we prefer bf16 inputs with f32 accumulation
(``preferred_element_type``), matching cuBLAS TF32/FP16 tensor-core behavior
in the reference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


@register_op("matmul")
def _matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    acc = jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else None
    out = jnp.matmul(x, y, preferred_element_type=acc)
    return out.astype(x.dtype) if acc is not None else out


@register_op("dot")
def _dot(x, y):
    return jnp.sum(x * y, axis=-1)


@register_op("bmm")
def _bmm(x, y):
    return jnp.matmul(x, y)


@register_op("mv")
def _mv(x, vec):
    return jnp.matmul(x, vec)


@register_op("outer")
def _outer(x, y):
    return jnp.outer(x, y)


@register_op("inner")
def _inner(x, y):
    return jnp.inner(x, y)


@register_op("cross")
def _cross(x, y, axis=None):
    ax = -1
    if axis is not None:
        ax = axis
    else:
        for i, s in enumerate(x.shape):
            if s == 3:
                ax = i
                break
    return jnp.cross(x, y, axis=ax)


@register_op("kron")
def _kron(x, y):
    return jnp.kron(x, y)


@register_op("addmm")
def _addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * jnp.matmul(x, y)


@register_op("einsum")
def _einsum(xs, equation=""):
    return jnp.einsum(equation, *xs)


@register_op("p_norm")
def _p_norm(x, porder=2.0, axis=None, keepdim=False, epsilon=1e-12):
    if porder == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if porder == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if porder == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    s = jnp.sum(jnp.abs(x) ** porder, axis=ax, keepdims=keepdim)
    return s ** (1.0 / porder)


@register_op("frobenius_norm")
def _frobenius_norm(x, axis=None, keepdim=False):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.sqrt(jnp.sum(x * x, axis=ax, keepdims=keepdim))


for _name, _fn in {
    "cholesky": jnp.linalg.cholesky,
    "inverse": jnp.linalg.inv,
    "pinv": jnp.linalg.pinv,
    "matrix_rank": jnp.linalg.matrix_rank,
    "slogdet": lambda x: tuple(jnp.linalg.slogdet(x)),
    "det": jnp.linalg.det,
}.items():
    register_op(_name)(_fn)


@register_op("qr")
def _qr(x, mode="reduced"):
    return tuple(jnp.linalg.qr(x, mode=mode))


@register_op("svd")
def _svd(x, full_matrices=False):
    u, s, vh = jnp.linalg.svd(x, full_matrices=full_matrices)
    return u, s, vh


def _host_eig(x):
    """Nonsymmetric eig has no TPU/XLA lowering on accelerators (the
    reference's eig kernel is CPU-only too, phi/kernels/cpu/
    eig_kernel.cc) — run it on host via pure_callback so it works under
    jit on every backend."""
    import numpy as np

    cdt = jnp.complex64 if x.dtype in (jnp.float32, jnp.complex64) \
        else jnp.complex128

    def cb(a):
        w, v = np.linalg.eig(np.asarray(a))
        return w.astype(cdt), v.astype(cdt)

    n = x.shape[-1]
    out_shape = (jax.ShapeDtypeStruct(x.shape[:-2] + (n,), cdt),
                 jax.ShapeDtypeStruct(x.shape, cdt))
    return jax.pure_callback(cb, out_shape, x, vmap_method="sequential")


@register_op("eig")
def _eig(x):
    return _host_eig(x)


@register_op("eigh")
def _eigh(x, UPLO="L"):
    return tuple(jnp.linalg.eigh(x, UPLO=UPLO))


@register_op("eigvals")
def _eigvals(x):
    import numpy as np

    cdt = jnp.complex64 if x.dtype in (jnp.float32, jnp.complex64) \
        else jnp.complex128

    def cb(a):
        return np.linalg.eigvals(np.asarray(a)).astype(cdt)

    # dedicated values-only callback: going through _host_eig would
    # materialize and transfer the n*n eigenvector matrix just to drop it
    out_shape = jax.ShapeDtypeStruct(x.shape[:-1], cdt)
    return jax.pure_callback(cb, out_shape, x, vmap_method="sequential")


@register_op("eigvalsh")
def _eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@register_op("matrix_power")
def _matrix_power(x, n):
    return jnp.linalg.matrix_power(x, int(n))


@register_op("solve")
def _solve(x, y):
    return jnp.linalg.solve(x, y)


@register_op("triangular_solve")
def _triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@register_op("cholesky_solve")
def _cholesky_solve(x, y, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


@register_op("lstsq")
def _lstsq(x, y, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@register_op("lu")
def _lu(x):
    lu, piv = jax.scipy.linalg.lu_factor(x)
    return lu, piv.astype(jnp.int32) + 1  # paddle pivots are 1-based


@register_op("histogram", nondiff=True)
def _histogram(x, bins=100, min=0, max=0):
    if min == 0 and max == 0:
        lo, hi = jnp.min(x), jnp.max(x)
    else:
        lo, hi = min, max
    h, _ = jnp.histogram(x, bins=int(bins), range=(lo, hi))
    return h.astype(jnp.int64)


# jit=False: output length is max(x)+1, a data-dependent shape.
@register_op("bincount", nondiff=True, jit=False)
def _bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=int(minlength))


@register_op("matrix_nms", nondiff=True, jit=False)
def _unavailable(*a, **k):
    raise NotImplementedError("matrix_nms pending detection-op milestone")

@register_op("cond")
def _cond(x, p=None):
    return jnp.linalg.cond(x, p=p)


@register_op("multi_dot")
def _multi_dot(xs):
    return jnp.linalg.multi_dot(list(xs))
