"""Linear algebra op implementations.

Analog of phi's matmul/blas family (/root/reference/paddle/phi/kernels/
matmul_kernel.h, funcs/blas/) and the linalg decompositions
(cholesky_kernel.h, svd_kernel.h, ...). Matmuls lower straight to the MXU via
``lax.dot_general``; on TPU we prefer bf16 inputs with f32 accumulation
(``preferred_element_type``), matching cuBLAS TF32/FP16 tensor-core behavior
in the reference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


@register_op("matmul")
def _matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    acc = jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else None
    out = jnp.matmul(x, y, preferred_element_type=acc)
    return out.astype(x.dtype) if acc is not None else out


@register_op("dot")
def _dot(x, y):
    return jnp.sum(x * y, axis=-1)


@register_op("bmm")
def _bmm(x, y):
    return jnp.matmul(x, y)


@register_op("mv")
def _mv(x, vec):
    return jnp.matmul(x, vec)


@register_op("outer")
def _outer(x, y):
    return jnp.outer(x, y)


@register_op("inner")
def _inner(x, y):
    return jnp.inner(x, y)


@register_op("cross")
def _cross(x, y, axis=None):
    ax = -1
    if axis is not None:
        ax = axis
    else:
        for i, s in enumerate(x.shape):
            if s == 3:
                ax = i
                break
    return jnp.cross(x, y, axis=ax)


@register_op("kron")
def _kron(x, y):
    return jnp.kron(x, y)


@register_op("addmm")
def _addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * jnp.matmul(x, y)


@register_op("einsum")
def _einsum(xs, equation=""):
    return jnp.einsum(equation, *xs)


@register_op("p_norm")
def _p_norm(x, porder=2.0, axis=None, keepdim=False, epsilon=1e-12):
    if porder == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if porder == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if porder == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    s = jnp.sum(jnp.abs(x) ** porder, axis=ax, keepdims=keepdim)
    return s ** (1.0 / porder)


@register_op("frobenius_norm")
def _frobenius_norm(x, axis=None, keepdim=False):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.sqrt(jnp.sum(x * x, axis=ax, keepdims=keepdim))


for _name, _fn in {
    "cholesky": jnp.linalg.cholesky,
    "inverse": jnp.linalg.inv,
    "pinv": jnp.linalg.pinv,
    "matrix_rank": jnp.linalg.matrix_rank,
    "slogdet": lambda x: tuple(jnp.linalg.slogdet(x)),
    "det": jnp.linalg.det,
}.items():
    register_op(_name)(_fn)


@register_op("qr")
def _qr(x, mode="reduced"):
    return tuple(jnp.linalg.qr(x, mode=mode))


@register_op("svd")
def _svd(x, full_matrices=False):
    u, s, vh = jnp.linalg.svd(x, full_matrices=full_matrices)
    return u, s, vh


def _host_eig(x):
    """Nonsymmetric eig has no TPU/XLA lowering on accelerators (the
    reference's eig kernel is CPU-only too, phi/kernels/cpu/
    eig_kernel.cc) — run it on host via pure_callback so it works under
    jit on every backend."""
    import numpy as np

    cdt = jnp.complex64 if x.dtype in (jnp.float32, jnp.complex64) \
        else jnp.complex128

    def cb(a):
        w, v = np.linalg.eig(np.asarray(a))
        return w.astype(cdt), v.astype(cdt)

    n = x.shape[-1]
    out_shape = (jax.ShapeDtypeStruct(x.shape[:-2] + (n,), cdt),
                 jax.ShapeDtypeStruct(x.shape, cdt))
    return jax.pure_callback(cb, out_shape, x, vmap_method="sequential")


@register_op("eig")
def _eig(x):
    return _host_eig(x)


@register_op("eigh")
def _eigh(x, UPLO="L"):
    return tuple(jnp.linalg.eigh(x, UPLO=UPLO))


@register_op("eigvals")
def _eigvals(x):
    import numpy as np

    cdt = jnp.complex64 if x.dtype in (jnp.float32, jnp.complex64) \
        else jnp.complex128

    def cb(a):
        return np.linalg.eigvals(np.asarray(a)).astype(cdt)

    # dedicated values-only callback: going through _host_eig would
    # materialize and transfer the n*n eigenvector matrix just to drop it
    out_shape = jax.ShapeDtypeStruct(x.shape[:-1], cdt)
    return jax.pure_callback(cb, out_shape, x, vmap_method="sequential")


@register_op("eigvalsh")
def _eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@register_op("matrix_power")
def _matrix_power(x, n):
    return jnp.linalg.matrix_power(x, int(n))


@register_op("solve")
def _solve(x, y):
    return jnp.linalg.solve(x, y)


@register_op("triangular_solve")
def _triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@register_op("cholesky_solve")
def _cholesky_solve(x, y, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


@register_op("lstsq")
def _lstsq(x, y, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@register_op("lu")
def _lu(x):
    lu, piv = jax.scipy.linalg.lu_factor(x)
    return lu, piv.astype(jnp.int32) + 1  # paddle pivots are 1-based


@register_op("histogram", nondiff=True)
def _histogram(x, bins=100, min=0, max=0):
    if min == 0 and max == 0:
        lo, hi = jnp.min(x), jnp.max(x)
    else:
        lo, hi = min, max
    h, _ = jnp.histogram(x, bins=int(bins), range=(lo, hi))
    return h.astype(jnp.int64)


# jit=False: output length is max(x)+1, a data-dependent shape.
@register_op("bincount", nondiff=True, jit=False)
def _bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=int(minlength))


def _mnms_iou(boxes, normalized):
    """Pairwise IoU [m, m] for [m, 4] xyxy boxes. Unnormalized boxes count
    inclusive pixels (+1), so touching integer boxes share a 1-pixel strip;
    overlap is zero only on strict separation per axis — the reference's
    JaccardOverlap convention (paddle/fluid/operators/detection/nms_util.h:71)."""
    import numpy as np
    off = 0.0 if normalized else 1.0
    lt = np.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = np.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    separated = (lt > rb).any(-1)
    wh = np.clip(rb - lt + off, 0.0, None)
    inter = np.where(separated, 0.0, wh[..., 0] * wh[..., 1])
    area = np.prod(boxes[:, 2:] - boxes[:, :2] + off, axis=1)
    return inter / (area[:, None] + area[None, :] - inter + 1e-10)


@register_op("matrix_nms", nondiff=True, jit=False)
def _matrix_nms(bboxes, scores, score_threshold=0.0, post_threshold=0.0,
                nms_top_k=-1, keep_top_k=-1, use_gaussian=False,
                gaussian_sigma=2.0, background_label=0, normalized=True):
    """Matrix NMS (SOLOv2): instead of hard suppression, every candidate's
    score decays by min_i f(iou_i,j)/f(comp_i) over all higher-scored boxes
    i, where comp_i is box i's own max-IoU with anything above it —
    entirely matrix arithmetic, no sequential suppression loop. Reference:
    paddle/fluid/operators/detection/matrix_nms_op.cc:1,
    python/paddle/fluid/layers/detection.py:3573 (API contract).

    bboxes [N, M, 4] xyxy, scores [N, C, M]. Returns (out [No, 6] rows of
    [label, score, x1, y1, x2, y2] sorted per image by decayed score,
    index [No, 1] absolute box indices n*M + m, rois_num [N]).
    Host-side numpy: the output count is data-dependent (jit=False, like
    bincount)."""
    import numpy as np
    B = np.asarray(bboxes)
    S = np.asarray(scores)
    N, M, _ = B.shape
    C = S.shape[1]
    dtype = S.dtype if S.dtype in (np.float32, np.float64) else np.float32
    det_rows, det_idx, rois_num = [], [], []
    for n in range(N):
        cls_l, score_l, box_l, idx_l = [], [], [], []
        for c in range(C):
            if c == background_label:
                continue
            sc = S[n, c]
            cand = np.where(sc > score_threshold)[0]
            if cand.size == 0:
                continue
            order = cand[np.argsort(-sc[cand], kind="stable")]
            if 0 <= nms_top_k < order.size:
                order = order[:nms_top_k]
            iou = np.triu(_mnms_iou(B[n, order], normalized), k=1)
            comp = iou.max(axis=0)          # box i's max IoU with its betters
            if use_gaussian:
                decay = np.exp((comp[:, None] ** 2 - iou ** 2)
                               * gaussian_sigma)
            else:
                # comp==1.0 (duplicate boxes) makes this x/0=inf or
                # 0/0=nan; both resolve correctly downstream (inf never
                # wins min() against a finite decay, nan propagates to a
                # score that fails the `> post_threshold` keep test) —
                # silence the RuntimeWarning they'd spray over test runs
                with np.errstate(divide="ignore", invalid="ignore"):
                    decay = (1.0 - iou) / (1.0 - comp[:, None])
            new_sc = sc[order] * decay.min(axis=0)
            # unconditional, like the reference kernel: even at
            # post_threshold=0 a fully-decayed (0.0) box is dropped
            keep = np.where(new_sc > post_threshold)[0]
            cls_l.append(np.full(keep.size, c, dtype))
            score_l.append(new_sc[keep].astype(dtype))
            box_l.append(B[n, order[keep]].astype(dtype))
            idx_l.append(n * M + order[keep])
        if cls_l:
            cls_a = np.concatenate(cls_l)
            score_a = np.concatenate(score_l)
            box_a = np.concatenate(box_l)
            idx_a = np.concatenate(idx_l)
            top = np.argsort(-score_a, kind="stable")
            if 0 <= keep_top_k < top.size:
                top = top[:keep_top_k]
            det_rows.append(np.concatenate(
                [cls_a[top, None], score_a[top, None], box_a[top]], axis=1))
            det_idx.append(idx_a[top])
            rois_num.append(top.size)
        else:
            rois_num.append(0)
    if det_rows:
        out = np.concatenate(det_rows).astype(dtype)
        index = np.concatenate(det_idx).astype(np.int64)[:, None]
    else:
        out = np.zeros((0, 6), dtype)
        index = np.zeros((0, 1), np.int64)
    return (jnp.asarray(out), jnp.asarray(index),
            jnp.asarray(np.asarray(rois_num, np.int32)))

@register_op("cond")
def _cond(x, p=None):
    return jnp.linalg.cond(x, p=p)


@register_op("multi_dot")
def _multi_dot(xs):
    return jnp.linalg.multi_dot(list(xs))
