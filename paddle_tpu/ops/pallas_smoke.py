"""TPU smoke gate for the Pallas kernel tier (r2 verdict item 1b).

Interpret-mode parity tests (tests/test_pallas.py) cannot catch Mosaic
*lowering* errors — the class of failure that killed BENCH_r02's GPT-2 and
BERT runs on hardware.  This gate executes every registered Pallas
override non-interpreted on the real backend at tiny shapes, fwd AND bwd,
before the kernels are allowed to serve real models.  Any failure flips
``FLAGS_use_pallas`` off (with a recorded warning) so a broken kernel
degrades to the lax path instead of crashing the model.

Reference analog: the reference gates fused kernels behind runtime
dispatch checks (operators/fused/fused_attention_op.cu input checks);
here the check is "does it actually compile+run on this chip".
"""
from __future__ import annotations

import warnings
from typing import Callable, Dict, Optional

from ..framework.flags import flag_value, set_flags

__all__ = ["run_smoke", "ensure", "last_report"]

_state: Dict[str, Optional[dict]] = {"report": None}


def _smoke_flash_attention():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from .pallas_kernels import flash_attention

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 256, 2, 64), jnp.float32)

    def loss(q, k, v):
        return flash_attention(q, k, v, is_causal=True).astype(
            jnp.float32).sum()

    val, grads = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))(
        q, q, q)
    jax.block_until_ready(grads)
    if not bool(jnp.isfinite(val)):
        raise FloatingPointError("flash attention smoke loss not finite")


def _smoke_fused_layer_norm():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from .pallas_kernels import fused_layer_norm

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(128, 256), jnp.float32)
    w = jnp.asarray(rng.randn(256), jnp.float32)
    b = jnp.asarray(rng.randn(256), jnp.float32)

    def loss(x, w, b):
        return fused_layer_norm(x, w, b).sum()

    val, grads = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))(
        x, w, b)
    jax.block_until_ready(grads)
    if not bool(jnp.isfinite(val)):
        raise FloatingPointError("fused LN smoke loss not finite")


def _smoke_fused_adamw():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from .pallas_kernels import fused_adamw

    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.randn(300, 7), jnp.float32)
    g = jnp.asarray(rng.randn(300, 7), jnp.float32)
    z = jnp.zeros_like(p)
    new_p, _, _ = fused_adamw(p, g, z, z, 1e-3, 0.9, 0.999, 1e-8, 0.01, 1)
    jax.block_until_ready(new_p)
    if not bool(jnp.isfinite(new_p.sum())):
        raise FloatingPointError("fused AdamW smoke output not finite")


def _smoke_ragged_paged_attention():
    """Fused serving kernel: a mixed decode + prefill-chunk ragged
    batch over a tiny block pool, non-interpreted — the lowering gate
    for the GenerationEngine(attention='fused') path."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from .ragged_paged_attention import ragged_layout, ragged_paged_attention

    rng = np.random.RandomState(0)
    H, BS, DH, S, T = 2, 16, 64, 2, 2
    pool = jnp.asarray(rng.randn(1, 2, 6, H, BS, DH), jnp.float32)
    tables = np.zeros((S, T), np.int32)
    tables[0, :2] = [1, 3]
    tables[1, :1] = [4]
    blk_seq, qstart, pos0, _, _ = ragged_layout([1, 9], [20, 0],
                                                q_bucket=24)
    q = jnp.asarray(rng.randn(H, 24, DH), jnp.float32)
    out = jax.jit(lambda q_, p_: ragged_paged_attention(
        q_, p_, 0, blk_seq, qstart, pos0, tables,
        np.zeros(S, np.int32), np.asarray([21, 9], np.int32)))(q, pool)
    jax.block_until_ready(out)
    if not bool(jnp.isfinite(out.sum())):
        raise FloatingPointError(
            "ragged paged attention smoke output not finite")


_KERNEL_SMOKES: Dict[str, Callable[[], None]] = {
    "flash_attention": _smoke_flash_attention,
    "fused_layer_norm": _smoke_fused_layer_norm,
    "fused_adamw": _smoke_fused_adamw,
    "ragged_paged_attention": _smoke_ragged_paged_attention,
}


def run_smoke() -> dict:
    """Execute every Pallas kernel non-interpreted on the current backend.

    Returns {"ok": bool, "backend": str, "kernels": {name: "ok"|error}}.
    Does NOT mutate flags — see ``ensure`` for the gate.
    """
    import jax

    report = {"backend": jax.default_backend(), "kernels": {}, "ok": True}
    for name, fn in _KERNEL_SMOKES.items():
        try:
            fn()
            report["kernels"][name] = "ok"
        except Exception as e:  # any compile/runtime failure must gate
            report["kernels"][name] = f"{type(e).__name__}: {e}"[:500]
            report["ok"] = False
    _state["report"] = report
    return report


def ensure() -> bool:
    """Gate: on TPU, smoke all kernels once; on any failure disable the
    Pallas tier (``FLAGS_use_pallas=False``) with a warning so models fall
    back to the lax compositions.  Returns True when the Pallas tier is
    enabled and healthy.  Off-TPU (tests run interpret-mode) this is a
    no-op returning the flag value.
    """
    from .pallas_kernels import _on_tpu

    if not flag_value("FLAGS_use_pallas"):
        return False
    if not _on_tpu():
        return True
    if _state["report"] is not None:
        return _state["report"]["ok"]
    report = run_smoke()
    if not report["ok"]:
        bad = {k: v for k, v in report["kernels"].items() if v != "ok"}
        set_flags({"FLAGS_use_pallas": False})
        warnings.warn(
            f"Pallas TPU smoke gate FAILED — disabling the Pallas kernel "
            f"tier (FLAGS_use_pallas=False); models use the lax fallback "
            f"path. Failures: {bad}")
    return report["ok"]


def last_report() -> Optional[dict]:
    return _state["report"]
