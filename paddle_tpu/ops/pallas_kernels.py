"""Pallas TPU kernels — the CUDA-analog tier.

Reference analogs: paddle/fluid/operators/fused/fused_attention_op.cu,
fmha_ref.h (flash attention), fused_dropout_helper.h + layer_norm_kernel
(fused LN), operators/optimizers/adam_op (fused optimizer update).

Design: every kernel registers as an *override* of the generic lax op
(ops/registry.py:register_override) guarded by a predicate — on TPU with
supported shapes the Pallas kernel runs; anywhere else the lax composition
stands. On CPU the kernels execute in Pallas interpret mode, which is how
the parity tests run them (SURVEY §4 OpTest ≙ numpy-vs-kernel parity).

Enablement: FLAGS_use_pallas (default True). Forced interpret-mode selection
for tests: FLAGS_pallas_force (runs kernels even off-TPU, interpreted).
"""
from __future__ import annotations

import contextlib
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..framework.flags import define_flag, flag_value
from .registry import register_op, register_override

define_flag("FLAGS_use_pallas", True,
            "use Pallas TPU kernels where registered")
define_flag("FLAGS_pallas_force", False,
            "force-select Pallas kernels off-TPU (interpret mode, tests)")

_NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _interpret() -> bool:
    # off-TPU the kernels can only run interpreted (tests)
    return not _on_tpu()


def _pallas_enabled() -> bool:
    if not flag_value("FLAGS_use_pallas"):
        return False
    return _on_tpu() or flag_value("FLAGS_pallas_force")


def _shape_of(x):
    return tuple(getattr(x, "shape", ()))


def _dtype_of(x):
    return getattr(x, "dtype", "float32")


def _x64_off():
    """Trace-scope guard: the framework enables jax x64 globally (reference
    parity for int64/float64 tensors), but under x64 Python-int constants
    inside kernel traces become int64 scalars that Mosaic cannot lower
    (infinite int64->int32 convert recursion / malformed mixed-type index
    arithmetic).  Every pallas_call invocation — which is when the kernel
    body is traced — runs under this x64-off scope; the surrounding jaxpr
    keeps its global setting."""
    try:
        from jax._src.config import enable_x64
        return enable_x64(False)
    except ImportError:  # future jax: fall back to no-op (x64 default off)
        return contextlib.nullcontext()


# ===========================================================================
# Flash attention (fwd + bwd), layout [B, S, H, D]
# ===========================================================================

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


# LSE (and the bwd delta) travel between kernels as [BH, S, LSE_LANES]
# fp32 with the value replicated across the trailing lane dim.  A plain
# [BH, S] layout with a (1, block_q) block violates the Mosaic tiling rule
# (second-to-last block dim must be divisible by 8 or equal the array dim)
# — the exact crash BENCH_r02 recorded on hardware.  With a trailing
# LSE_LANES=8 dim, blocks are (1, block_q, 8): block_q is sublane-aligned
# and the last block dim equals the array dim, so the layout is legal on
# TPU at an 8x (not 128x) replication cost.
LSE_LANES = 8


def _fa_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                   m_scr, l_scr, acc_scr, *, scale, causal,
                   block_q, block_k, n_k):
    """One (q-block, k-block) tile of streaming flash attention.

    Grid (bh, nq, nk): the k dimension iterates INNERMOST and
    sequentially on a TPU core, so the online-softmax stats live in VMEM
    scratch across k steps — K/V stream through the grid in blocks and
    the kernel never maps the full sequence (the r3-v1 kernel's VMEM
    bound). i32-typed block-size constants: bare python ints in kernel
    index math get materialized as i64 by Mosaic.
    """
    _I32_BQ = jnp.int32(block_q)
    _I32_BK = jnp.int32(block_k)
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: tiles strictly above the diagonal contribute nothing
    needed = True
    if causal:
        needed = kj * _I32_BK <= (qi + 1) * _I32_BQ - 1

    @pl.when(needed)
    def _update():
        # operands stay in their storage dtype (bf16 runs the MXU at native
        # rate); preferred_element_type=f32 keeps the ACCUMULATION in f32 —
        # upcasting operands first would force fp32-rate matmuls
        q = q_ref[0]                                  # [bq, D]
        bq, d = q.shape
        k_blk = k_ref[0]                              # [bk, D]
        v_blk = v_ref[0]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [bq, bk]
        if causal:
            rows = qi * _I32_BQ + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            cols = kj * _I32_BK + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_prev = m_scr[...]
        l_prev = l_scr[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)         # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == n_k - 1)
    def _finalize():
        bq = acc_scr.shape[0]
        l_safe = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(
            m_scr[...] + jnp.log(l_safe), (bq, LSE_LANES))


def _fa_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                  dq_scr, *, scale, causal, block_q, block_k, n_k):
    _I32_BQ = jnp.int32(block_q)
    _I32_BK = jnp.int32(block_k)
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    needed = True
    if causal:
        needed = kj * _I32_BK <= (qi + 1) * _I32_BQ - 1

    @pl.when(needed)
    def _update():
        # native-dtype operands + f32 accumulation (see fwd kernel note)
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]                        # [bq, 1] of [bq, 8]
        delta = delta_ref[0][:, :1]
        bq, d = q.shape
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qi * _I32_BQ + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            cols = kj * _I32_BK + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[...] = dq_scr[...] + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == n_k - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _fa_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                   block_q, block_k, n_q):
    _I32_BQ = jnp.int32(block_q)
    _I32_BK = jnp.int32(block_k)
    ki = pl.program_id(1)
    qj = pl.program_id(2)

    @pl.when(qj == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    needed = True
    if causal:
        # rows >= cols somewhere in the tile: last row of this q block
        # must reach the first col of this k block
        needed = (qj + 1) * _I32_BQ - 1 >= ki * _I32_BK

    @pl.when(needed)
    def _update():
        # native-dtype operands + f32 accumulation (see fwd kernel note)
        k = k_ref[0]                                  # [bk, D]
        v = v_ref[0]
        bk, d = k.shape
        q_blk = q_ref[0]                              # [bq, D]
        do_blk = do_ref[0]
        lse_blk = lse_ref[0][:, :1]                   # [bq, 1]
        delta_blk = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        if causal:
            rows = qj * _I32_BQ + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            cols = ki * _I32_BK + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_blk)
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk) * scale
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds.astype(q_blk.dtype), q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qj == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _fa_call_fwd(q, k, v, scale, causal, block_q, block_k):
    """q,k,v: [BH, S, D] -> (o [BH, Sq, D], lse [BH, Sq, LSE_LANES])."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq = sq // block_q
    nk = sk // block_k
    kernel = functools.partial(
        _fa_fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, n_k=nk)
    with _x64_off():
        return pl.pallas_call(
            kernel,
            grid=(bh, nq, nk),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, LSE_LANES),
                             lambda b, i, j: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
                jax.ShapeDtypeStruct((bh, sq, LSE_LANES), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
            interpret=_interpret(),
        )(q, k, v)


def _fa_call_bwd(q, k, v, o, lse, do, scale, causal, block_q, block_k):
    bh, sq, d = q.shape
    sk = k.shape[1]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)                 # [BH, Sq, 1]
    delta = jnp.broadcast_to(delta, (bh, sq, LSE_LANES))
    with _x64_off():
        dq = pl.pallas_call(
            functools.partial(_fa_dq_kernel, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k,
                              n_k=sk // block_k),
            grid=(bh, sq // block_q, sk // block_k),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, LSE_LANES),
                             lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, LSE_LANES),
                             lambda b, i, j: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, d),
                                   lambda b, i, j: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            interpret=_interpret(),
        )(q, k, v, do, lse, delta)
        dk, dv = pl.pallas_call(
            functools.partial(_fa_dkv_kernel, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k,
                              n_q=sq // block_q),
            grid=(bh, sk // block_k, sq // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_q, LSE_LANES),
                             lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_q, LSE_LANES),
                             lambda b, i, j: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
                jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
            ],
            scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32)],
            interpret=_interpret(),
        )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _fa_fwd_kernel_resident(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                   block_q, block_k, seq_k):
    # i32-typed block-size constants: bare python ints in fori_loop bodies
    # get materialized as i64 by Mosaic, producing malformed mixed-type
    # index arithmetic on TPU
    _I32_BQ = jnp.int32(block_q)
    _I32_BK = jnp.int32(block_k)
    qi = pl.program_id(1)
    q = q_ref[0]                                  # [bq, D] (native dtype)
    bq, d = q.shape
    nk_full = seq_k // block_k
    if causal:
        # kv blocks beyond the diagonal contribute nothing
        nk = jnp.minimum(nk_full, ((qi + 1) * block_q + block_k - 1)
                         // block_k)
    else:
        nk = nk_full

    def body(j, carry):
        # running softmax stats stay 2D [bq, 1] (sublane-oriented);
        # rank-1 carries would force lane<->sublane relayouts in Mosaic
        m_prev, l_prev, acc = carry
        k_blk = k_ref[0, pl.ds(j * _I32_BK, block_k), :]
        v_blk = v_ref[0, pl.ds(j * _I32_BK, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [bq, bk]
        if causal:
            rows = qi * _I32_BQ + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            cols = j * _I32_BK + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)         # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nk, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = jnp.broadcast_to(m + jnp.log(l_safe), (bq, LSE_LANES))


def _fa_dq_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                  *, scale, causal, block_q, block_k, seq_k):
    _I32_BQ = jnp.int32(block_q)
    _I32_BK = jnp.int32(block_k)
    qi = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0][:, :1]                        # [bq, 1] of [bq, 8]
    delta = delta_ref[0][:, :1]
    bq, d = q.shape
    nk_full = seq_k // block_k
    nk = jnp.minimum(nk_full, ((qi + 1) * block_q + block_k - 1) //
                     block_k) if causal else nk_full

    def body(j, dq):
        k_blk = k_ref[0, pl.ds(j * _I32_BK, block_k), :]
        v_blk = v_ref[0, pl.ds(j * _I32_BK, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qi * _I32_BQ + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            cols = j * _I32_BK + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, nk, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _fa_dkv_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dk_ref, dv_ref, *, scale, causal, block_q, block_k,
                   seq_q):
    _I32_BQ = jnp.int32(block_q)
    _I32_BK = jnp.int32(block_k)
    ki = pl.program_id(1)
    k = k_ref[0]                                  # [bk, D] (native dtype)
    v = v_ref[0]
    bk, d = k.shape
    nq_full = seq_q // block_q
    start_q = (ki * block_k) // block_q if causal else 0

    def body(j, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(j * _I32_BQ, block_q), :]
        do_blk = do_ref[0, pl.ds(j * _I32_BQ, block_q), :]
        lse_blk = lse_ref[0, pl.ds(j * _I32_BQ, block_q), :1]   # [bq, 1]
        delta_blk = delta_ref[0, pl.ds(j * _I32_BQ, block_q), :1]
        s = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        if causal:
            rows = j * _I32_BQ + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            cols = ki * _I32_BK + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_blk)
        dv_new = dv + jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk) * scale
        dk_new = dk + jax.lax.dot_general(
            ds.astype(q_blk.dtype), q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    z = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_q, nq_full, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _fa_call_fwd_resident(q, k, v, scale, causal, block_q, block_k):
    """q,k,v: [BH, S, D] -> (o [BH, Sq, D], lse [BH, Sq, LSE_LANES])."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq = sq // block_q
    kernel = functools.partial(
        _fa_fwd_kernel_resident, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_k=sk)
    with _x64_off():
        return pl.pallas_call(
        kernel,
        grid=(bh, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, LSE_LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, LSE_LANES), jnp.float32),
        ],
            interpret=_interpret(),
        )(q, k, v)


def _fa_call_bwd_resident(q, k, v, o, lse, do, scale, causal, block_q, block_k):
    bh, sq, d = q.shape
    sk = k.shape[1]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)                 # [BH, Sq, 1]
    delta = jnp.broadcast_to(delta, (bh, sq, LSE_LANES))
    with _x64_off():
        dq = pl.pallas_call(
        functools.partial(_fa_dq_kernel_resident, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_k=sk),
        grid=(bh, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, LSE_LANES), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, LSE_LANES), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            interpret=_interpret(),
        )(q, k, v, do, lse, delta)
        dk, dv = pl.pallas_call(
        functools.partial(_fa_dkv_kernel_resident, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_q=sq),
        grid=(bh, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, sq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sq, LSE_LANES), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sq, LSE_LANES), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
            interpret=_interpret(),
        )(q, k, v, do, lse, delta)
    return dq, dk, dv



# ---------------------------------------------------------------------------
# kernel variant dispatch: the RESIDENT kernels map full K/V into VMEM
# (fastest: one kernel invocation per q block, measured 1.4x the
# streaming variant at s=1024) but cap the sequence at VMEM; the
# STREAMING kernels above block K/V through a 3D grid with scratch
# carries and have no sequence cap (32k+ tested on hardware). Pick per
# shape.
# ---------------------------------------------------------------------------

_RESIDENT_VMEM_ELEMS = 1_500_000  # (sq + sk) * d fp32 budget, ~6MB x2


def _use_resident(sq, sk, d):
    return (sq + sk) * d <= _RESIDENT_VMEM_ELEMS


def _fa_dispatch_fwd(q, k, v, scale, causal, block_q, block_k):
    bh, sq, d = q.shape
    sk = k.shape[1]
    if _use_resident(sq, sk, d):
        return _fa_call_fwd_resident(q, k, v, scale, causal, block_q,
                                     block_k)
    return _fa_call_fwd(q, k, v, scale, causal, block_q, block_k)


def _fa_dispatch_bwd(q, k, v, o, lse, do, scale, causal, block_q,
                     block_k):
    bh, sq, d = q.shape
    sk = k.shape[1]
    if _use_resident(sq, sk, d):
        return _fa_call_bwd_resident(q, k, v, o, lse, do, scale, causal,
                                     block_q, block_k)
    return _fa_call_bwd(q, k, v, o, lse, do, scale, causal, block_q,
                        block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_bhsd(q, k, v, scale, causal, block_q, block_k):
    o, _ = _fa_dispatch_fwd(q, k, v, scale, causal, block_q, block_k)
    return o


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k):
    o, lse = _fa_dispatch_fwd(q, k, v, scale, causal, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(scale, causal, block_q, block_k, res, do):
    q, k, v, o, lse = res
    return _fa_dispatch_bwd(q, k, v, o, lse, do, scale, causal, block_q,
                            block_k)


_flash_attention_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, is_causal=False, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Flash attention on [B, S, H, D] inputs (the framework's attention
    layout). Differentiable via the Pallas backward kernels."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"flash_attention needs seq lengths divisible by the block "
            f"sizes: sq={sq} %% {block_q}, sk={sk} %% {block_k}")
    # [B,S,H,D] -> [B*H, S, D]
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    o = _flash_attention_bhsd(qt, kt, vt, float(s), bool(is_causal),
                              int(block_q), int(block_k))
    return o.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


# Measured crossover on v5e (BENCH r3): at seq 128 XLA's native fused
# attention beats the flash kernel (BERT 47.6 vs 35.9 steps/s — the full
# S^2 matrix is tiny and XLA's bf16 fusion wins), while at seq 1024 the
# flash kernel wins 1.16x (GPT-2). This heuristic is only the DEFAULT:
# the shape-class autotune cache (ops/autotune_cache.py, r3 verdict
# item 9) overrides it wherever a measured winner is recorded, and
# tune_attention() records winners per device kind.
FLASH_MIN_SEQ = 512


def _sdpa_key(b, h, sq, sk, d, dtype, is_causal):
    from . import autotune_cache as _at
    # tune=bwd2: key-format version. Pre-r5 entries were measured
    # fwd-only at default blocks; the r5 tuner measures fwd+bwd across
    # block configs — stale entries must miss, not veto the new search.
    return _at.shape_class(b * h, sq, sk, d, dtype=str(dtype),
                           causal=bool(is_causal), tune="bwd2")


def _fa_supported(q, k, v, mask, dropout_key, dropout_p, is_causal,
                  block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    qs, ks = _shape_of(q), _shape_of(k)
    if len(qs) != 4 or mask is not None or (dropout_p or 0.0) > 0.0:
        return False
    b, sq, h, d = qs
    sk = ks[1]
    if is_causal and sq != sk:
        return False
    bq, bk = min(block_q, sq), min(block_k, sk)
    # structural requirements first — an unlowrable shape never dispatches
    # to Pallas regardless of what the cache says.
    # streaming kernels: VMEM holds only (block_q + 2*block_k) x d tiles
    # plus scratch regardless of sequence length, so there is no seq cap —
    # long context is bounded by HBM for Q/K/V themselves (e.g. 128k x 128
    # bf16 = 32MB per head-batch).
    if not (sq % bq == 0 and sk % bk == 0 and d <= 256 and
            sq >= 8 and sk >= 8):
        return False
    if flag_value("FLAGS_pallas_force"):
        return True
    from . import autotune_cache as _at
    default = "pallas" if max(sq, sk) >= FLASH_MIN_SEQ else "lax"
    choice = _at.choose("scaled_dot_product_attention",
                        _sdpa_key(b, h, sq, sk, d, _dtype_of(q),
                                  is_causal),
                        default=default)
    return choice.startswith("pallas")   # incl. "pallas:BQxBK" configs


# block-size search space for tune_attention (r4 verdict item 3: the
# flash bwd was undertuned at the default 128x128). Unlowerable or
# non-dividing combos simply fail their measurement and never win.
_TUNE_BLOCKS = [(128, 128), (256, 128), (128, 256), (256, 256)]


def tune_attention(q, a_k, v, is_causal=False, persist=True,
                   include_bwd=True, skip_if_cached=False):
    """Measure lax vs pallas (across block-size configs) for this shape
    class on CONCRETE arrays and record the winner in the autotune cache
    (the reference's warmup-step measurement, made explicit). With
    ``include_bwd`` the timed quantity is a full fwd+bwd — the training
    crossover, which is what the benches dispatch on. Returns the
    winning tier name (``lax``, ``pallas``, or ``pallas:BQxBK``)."""
    import jax.numpy as jnp

    from . import autotune_cache as _at
    from .registry import get_op

    q = jnp.asarray(q._data if hasattr(q, "_data") else q)
    a_k = jnp.asarray(a_k._data if hasattr(a_k, "_data") else a_k)
    v = jnp.asarray(v._data if hasattr(v, "_data") else v)
    b, sq, h, d = q.shape
    sk = a_k.shape[1]
    key = _sdpa_key(b, h, sq, sk, d, q.dtype, is_causal)
    if skip_if_cached:
        got = _at.choose("scaled_dot_product_attention", key, default="")
        if got:
            return got    # measured in an earlier run; cache persists
    lax_fn = get_op("scaled_dot_product_attention").fn

    def thunk(f):
        if not include_bwd:
            jf = jax.jit(f)
            return lambda: jf(q, a_k, v)
        jg = jax.jit(jax.grad(
            lambda q_, k_, v_: f(q_, k_, v_).astype(jnp.float32).sum(),
            argnums=(0, 1, 2)))
        return lambda: jg(q, a_k, v)

    candidates = {
        "lax": thunk(functools.partial(lax_fn, is_causal=is_causal))}
    seen_effective = set()
    for bq, bk in _TUNE_BLOCKS:
        # dedup on the CLAMPED blocks: at short seq several configs
        # collapse to the same kernel — measuring it repeatedly under
        # different names is pure tuning-budget waste
        eff = (min(bq, sq), min(bk, sk))
        if eff in seen_effective:
            continue
        seen_effective.add(eff)
        if eff == (min(DEFAULT_BLOCK_Q, sq), min(DEFAULT_BLOCK_K, sk)):
            name = "pallas"       # default blocks keep the plain name
        else:
            name = f"pallas:{bq}x{bk}"
        candidates[name] = thunk(functools.partial(
            flash_attention, is_causal=is_causal, block_q=bq, block_k=bk))
    return _at.measure("scaled_dot_product_attention", key, candidates,
                       persist=persist)


def _tuned_blocks(q, k, is_causal):
    """Dispatch-time lookup of the measured block config (host-side dict
    read; shapes are static under trace). Falls back to the defaults
    when the tuned blocks do not divide THIS shape — the pow2-bucketed
    shape class can contain members the winning config cannot tile."""
    from . import autotune_cache as _at
    b, sq, h, d = _shape_of(q)
    sk = _shape_of(k)[1]
    choice = _at.choose(
        "scaled_dot_product_attention",
        _sdpa_key(b, h, sq, sk, d, _dtype_of(q), is_causal),
        default="pallas")
    if choice.startswith("pallas:"):
        try:
            bq, bk = (int(x) for x in choice.split(":", 1)[1].split("x"))
        except ValueError:
            import warnings
            warnings.warn(f"malformed autotune entry {choice!r}; using "
                          f"default flash blocks", RuntimeWarning)
            return DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K
        if sq % min(bq, sq) == 0 and sk % min(bk, sk) == 0:
            return bq, bk
    return DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K


def _sdpa_pallas(q, k, v, mask=None, dropout_key=None, dropout_p=0.0,
                 is_causal=False, scale=None):
    bq, bk = _tuned_blocks(q, k, is_causal)
    return flash_attention(q, k, v, is_causal=is_causal, scale=scale,
                           block_q=bq, block_k=bk)


register_override(
    "scaled_dot_product_attention",
    lambda args, attrs: _pallas_enabled() and _fa_supported(
        args[0], args[1], args[2],
        args[3] if len(args) > 3 else attrs.get("mask"),
        args[4] if len(args) > 4 else attrs.get("dropout_key"),
        attrs.get("dropout_p", 0.0), attrs.get("is_causal", False)),
)(_sdpa_pallas)


# ===========================================================================
# Fused LayerNorm (last axis, affine) — fwd kernel + recompute bwd kernel
# ===========================================================================

LN_BLOCK_ROWS = 128


def _ln_fwd_kernel(x_ref, w_ref, b_ref, o_ref, *, eps):
    # w_ref/b_ref are [1, D]: rank-1 blocks have no legal TPU layout for
    # arbitrary D, and [1, D] broadcasts against [rows, D] for free
    x = x_ref[...].astype(jnp.float32)            # [rows, D]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd * w_ref[...].astype(jnp.float32) + \
        b_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def _ln_bwd_kernel(x_ref, w_ref, g_ref, dx_ref, dwp_ref, dbp_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)            # [1, D]
    d = x.shape[-1]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    gw = g * w
    m1 = jnp.mean(gw, axis=-1, keepdims=True)
    m2 = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx = (gw - m1 - xhat * m2) * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)
    # per-row-block partial reductions for dw/db. The partials carry an
    # 8-sublane middle dim ([nb, 8, D] overall) because a (1, D) block
    # over an [nb, D] array is tiling-illegal on TPU; each partial is
    # spread evenly over its 8 sublanes so the caller's plain sum over
    # (nb, 8) recovers the exact total.
    dwp_ref[0] = jnp.broadcast_to(
        jnp.sum(g * xhat, axis=0, keepdims=True) / 8.0, (8, x.shape[-1]))
    dbp_ref[0] = jnp.broadcast_to(
        jnp.sum(g, axis=0, keepdims=True) / 8.0, (8, x.shape[-1]))


def _ln_reshape(x):
    d = x.shape[-1]
    rows = x.size // d
    return x.reshape(rows, d), rows, d


def _ln_block_rows(rows, d):
    """Row-block size bounded by a ~4MB-per-buffer VMEM budget (the bwd
    kernel holds three row blocks at fp32)."""
    budget_rows = max(8, (4 * 2 ** 20) // (d * 4))
    return min(LN_BLOCK_ROWS, rows, budget_rows)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_layer_norm_2d(x2, w, b, eps):
    """x2: [rows, D]; w, b: [1, D]."""
    rows, d = x2.shape
    br = _ln_block_rows(rows, d)
    with _x64_off():
        return pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            # w/b are [1, D] arrays: block == full array dim on both
            # axes, the legal-by-equality case of the tiling rule
            pl.BlockSpec((1, d), lambda i: (0, 0)),  # lint: ok
            pl.BlockSpec((1, d), lambda i: (0, 0)),  # lint: ok
        ],
        out_specs=pl.BlockSpec((br, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x2.dtype),
            interpret=_interpret(),
        )(x2, w, b)


def _ln_fwd_rule(x2, w, b, eps):
    return _fused_layer_norm_2d(x2, w, b, eps), (x2, w, b)


def _ln_bwd_rule(eps, res, g):
    x2, w, b = res
    b_dtype = b.dtype
    rows, d = x2.shape
    br = _ln_block_rows(rows, d)
    nb = rows // br
    with _x64_off():
        dx, dwp, dbp = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, eps=eps),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            # w is a [1, D] array: block == full array (legal equality)
            pl.BlockSpec((1, d), lambda i: (0, 0)),  # lint: ok
            pl.BlockSpec((br, d), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((1, 8, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 8, d), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, d), x2.dtype),
            jax.ShapeDtypeStruct((nb, 8, d), jnp.float32),
            jax.ShapeDtypeStruct((nb, 8, d), jnp.float32),
        ],
            interpret=_interpret(),
        )(x2, w, g)
    return (dx, dwp.sum((0, 1), keepdims=False)[None, :].astype(w.dtype),
            dbp.sum((0, 1), keepdims=False)[None, :].astype(b_dtype))


_fused_layer_norm_2d.defvjp(_ln_fwd_rule, _ln_bwd_rule)


def fused_layer_norm(x, weight, bias, epsilon=1e-5):
    """LayerNorm over the last axis with affine params, as one Pallas
    kernel per row-block (reference: fused LN in fused_dropout_helper.h)."""
    x2, rows, d = _ln_reshape(x)
    br = _ln_block_rows(rows, d)
    if rows % br:
        raise ValueError(
            f"fused_layer_norm needs total rows ({rows}) divisible by the "
            f"row block ({br})")
    b = bias if bias is not None else jnp.zeros((d,), x.dtype)
    out = _fused_layer_norm_2d(x2, weight.reshape(1, d), b.reshape(1, d),
                               float(epsilon))
    return out.reshape(x.shape)


def _ln_supported(x, weight, bias, begin_norm_axis):
    xs = _shape_of(x)
    if not xs or weight is None:
        return False
    if begin_norm_axis is not None and begin_norm_axis != len(xs) - 1:
        return False
    d = xs[-1]
    rows = 1
    for s in xs[:-1]:
        rows *= s
    if rows == 0 or d < 8 or d > 16384:
        return False
    return rows % _ln_block_rows(rows, d) == 0


register_override(
    "layer_norm",
    lambda args, attrs: _pallas_enabled() and _ln_supported(
        args[0],
        args[1] if len(args) > 1 else attrs.get("weight"),
        args[2] if len(args) > 2 else attrs.get("bias"),
        attrs.get("begin_norm_axis")),
)(lambda x, weight=None, bias=None, epsilon=1e-5, begin_norm_axis=None:
  fused_layer_norm(x, weight, bias, epsilon))


# ===========================================================================
# Fused AdamW update — one elementwise kernel for (p, m, v) (reference:
# operators/optimizers/adam_op.cu / merged_adam)
# ===========================================================================

def _adamw_kernel(p_ref, g_ref, m_ref, v_ref, sc_ref,
                  new_p_ref, new_m_ref, new_v_ref):
    lr, b1, b2, eps, wd, bc1, bc2 = (sc_ref[0], sc_ref[1], sc_ref[2],
                                     sc_ref[3], sc_ref[4], sc_ref[5],
                                     sc_ref[6])
    g = g_ref[...].astype(jnp.float32)
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    pf = p_ref[...].astype(jnp.float32)
    mhat = m / bc1
    vhat = v / bc2
    new_p = pf - lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * pf)
    new_p_ref[...] = new_p.astype(new_p_ref.dtype)
    new_m_ref[...] = m
    new_v_ref[...] = v


@functools.lru_cache(maxsize=1024)
def _fused_adamw_callable(shape, dtype_name, interpret):
    """One jitted (pad → kernel → unpad) callable per param shape/dtype —
    the eager step hits this cache instead of re-tracing every call."""
    dtype = jnp.dtype(dtype_name)
    n = 1
    for s in shape:
        n *= s
    lanes = 128
    rows = max(1, (n + lanes - 1) // lanes)
    pad = rows * lanes - n

    def run(p, g, m, v, scalars):
        def flat(a, dt):
            a = a.reshape(-1).astype(dt)
            if pad:
                a = jnp.pad(a, (0, pad))
            return a.reshape(rows, lanes)

        with _x64_off():
            new_p, new_m, new_v = pl.pallas_call(
            _adamw_kernel,
            in_specs=[pl.BlockSpec((rows, lanes), lambda: (0, 0)),
                      pl.BlockSpec((rows, lanes), lambda: (0, 0)),
                      pl.BlockSpec((rows, lanes), lambda: (0, 0)),
                      pl.BlockSpec((rows, lanes), lambda: (0, 0)),
                      pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=[pl.BlockSpec((rows, lanes), lambda: (0, 0)),
                       pl.BlockSpec((rows, lanes), lambda: (0, 0)),
                       pl.BlockSpec((rows, lanes), lambda: (0, 0))],
            out_shape=[jax.ShapeDtypeStruct((rows, lanes), dtype),
                       jax.ShapeDtypeStruct((rows, lanes), jnp.float32),
                       jax.ShapeDtypeStruct((rows, lanes), jnp.float32)],
                interpret=interpret,
            )(flat(p, dtype), flat(g, jnp.float32), flat(m, jnp.float32),
              flat(v, jnp.float32), scalars)

        def unflat(a, dt):
            return a.reshape(-1)[:n].reshape(shape).astype(dt)

        return (unflat(new_p, dtype), unflat(new_m, jnp.float32),
                unflat(new_v, jnp.float32))

    return jax.jit(run)


def fused_adamw(p, g, m, v, lr, beta1, beta2, eps, weight_decay, step):
    """Fused AdamW on a flattened parameter. Returns (new_p, new_m, new_v)."""
    bc1 = 1.0 - beta1 ** step
    bc2 = 1.0 - beta2 ** step
    scalars = jnp.asarray([lr, beta1, beta2, eps, weight_decay, bc1, bc2],
                          jnp.float32)
    fn = _fused_adamw_callable(tuple(p.shape), jnp.dtype(p.dtype).name,
                               _interpret())
    return fn(p, g, m, v, scalars)


def fused_adamw_available() -> bool:
    return _pallas_enabled()
