"""Tensor creation & random op implementations.

Analog of phi's full/empty/arange/gaussian/uniform kernels
(/root/reference/paddle/phi/kernels/full_kernel.h, gaussian_random_kernel.h,
uniform_random_kernel.h) — jax PRNG keys replace the reference's per-device
curand generators (phi/core/generator.cc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("full", nondiff=True)
def _full(shape=(), fill_value=0.0, dtype=None):
    return jnp.full(tuple(shape), fill_value, dtype=dtype)


@register_op("arange", nondiff=True)
def _arange(start=0, end=None, step=1, dtype=None):
    return jnp.arange(start, end, step, dtype=dtype)


@register_op("linspace", nondiff=True)
def _linspace(start, stop, num, dtype=None):
    return jnp.linspace(start, stop, int(num), dtype=dtype)


@register_op("logspace", nondiff=True)
def _logspace(start, stop, num, base=10.0, dtype=None):
    return jnp.logspace(start, stop, int(num), base=base, dtype=dtype)


@register_op("eye", nondiff=True)
def _eye(num_rows, num_columns=None, dtype=None):
    return jnp.eye(int(num_rows),
                   int(num_columns) if num_columns is not None else None,
                   dtype=dtype)


@register_op("full_like", nondiff=True)
def _full_like(x, fill_value, dtype=None):
    return jnp.full_like(x, fill_value, dtype=dtype)


@register_op("tril")
def _tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@register_op("triu")
def _triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


@register_op("diag")
def _diag(x, offset=0, padding_value=0):
    if x.ndim == 1 and padding_value != 0:
        d = jnp.diag(x, k=offset)
        mask = jnp.eye(d.shape[0], d.shape[1], k=offset, dtype=bool)
        return jnp.where(mask, d, jnp.asarray(padding_value, d.dtype))
    return jnp.diag(x, k=offset)


@register_op("diagflat")
def _diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


@register_op("diag_embed")
def _diag_embed(x, offset=0, dim1=-2, dim2=-1):
    out = jnp.zeros(x.shape + (x.shape[-1],), x.dtype)
    out = jnp.vectorize(lambda v: jnp.diag(v, k=offset),
                        signature="(n)->(m,m)")(x)
    if (dim1, dim2) != (-2, -1):
        out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
    return out


@register_op("diagonal")
def _diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


@register_op("meshgrid")
def _meshgrid(xs):
    return tuple(jnp.meshgrid(*xs, indexing="ij"))


@register_op("assign")
def _assign(x):
    return jnp.asarray(x)


@register_op("cast")
def _cast(x, dtype):
    return x.astype(dtype)


# -- random (keys passed explicitly as array args, see framework.random) ----

@register_op("uniform_random", nondiff=True)
def _uniform(key, shape=(), dtype="float32", min=-1.0, max=1.0):
    return jax.random.uniform(key, tuple(shape), dtype=jnp.dtype(dtype),
                              minval=min, maxval=max)


@register_op("gaussian_random", nondiff=True)
def _gaussian(key, shape=(), dtype="float32", mean=0.0, std=1.0):
    return mean + std * jax.random.normal(key, tuple(shape),
                                          dtype=jnp.dtype(dtype))


@register_op("randint", nondiff=True)
def _randint(key, low, high=None, shape=(), dtype="int64"):
    if high is None:
        low, high = 0, low
    return jax.random.randint(key, tuple(shape), low, high,
                              dtype=jnp.dtype(dtype))


@register_op("randperm", nondiff=True)
def _randperm(key, n, dtype="int64"):
    return jax.random.permutation(key, int(n)).astype(dtype)


@register_op("bernoulli", nondiff=True)
def _bernoulli(key, p):
    return jax.random.bernoulli(key, p).astype(p.dtype)


@register_op("multinomial", nondiff=True)
def _multinomial(key, x, num_samples=1, replacement=False):
    logits = jnp.log(jnp.maximum(x, 1e-30))
    if replacement:
        return jax.random.categorical(
            key, logits, axis=-1,
            shape=x.shape[:-1] + (int(num_samples),)).astype(jnp.int64)
    # without replacement: gumbel top-k
    g = jax.random.gumbel(key, x.shape, dtype=jnp.float32)
    _, idx = jax.lax.top_k(logits + g, int(num_samples))
    return idx.astype(jnp.int64)


@register_op("standard_gamma", nondiff=True)
def _standard_gamma(key, alpha):
    return jax.random.gamma(key, alpha)


@register_op("poisson", nondiff=True)
def _poisson(key, x):
    return jax.random.poisson(key, x).astype(x.dtype)


@register_op("exponential", nondiff=True)
def _exponential(key, x, lam=1.0):
    return jax.random.exponential(key, x.shape, x.dtype) / lam


@register_op("dropout_raw", nondiff=False)
def _dropout(x, key, p=0.5, axis=None, training=True,
             mode="upscale_in_train"):
    # reference: phi/kernels/dropout_kernel.h semantics; axis ≙ the
    # reference's dropout_nd (mask drawn on the given axes, broadcast over
    # the rest — dropout2d/3d channel-wise masks).
    if not training or p == 0.0:
        return x
    if p == 1.0:
        return jnp.zeros_like(x)
    keep = 1.0 - p
    if axis is None:
        mask_shape = x.shape
    else:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        axes = tuple(a % x.ndim for a in axes)
        mask_shape = tuple(d if i in axes else 1
                           for i, d in enumerate(x.shape))
    mask = jax.random.bernoulli(key, keep, mask_shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)
