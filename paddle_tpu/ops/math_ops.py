"""Elementwise math, reduction, and comparison op implementations.

Analog of the reference's phi kernels for the elementwise / reduce / compare
families (/root/reference/paddle/phi/kernels/elementwise_*.h, reduce_*.h,
cpu|gpu/*_kernel.cc|cu). Each impl is a pure jax function over arrays; XLA
fuses chains of these into single kernels, replacing the reference's
hand-fused variants.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

# -- binary elementwise -----------------------------------------------------

for _name, _fn in {
    "add": jnp.add,
    "subtract": jnp.subtract,
    "multiply": jnp.multiply,
    "divide": jnp.divide,
    "floor_divide": jnp.floor_divide,
    "mod": jnp.mod,
    "remainder": jnp.remainder,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "fmax": jnp.fmax,
    "fmin": jnp.fmin,
    "atan2": jnp.arctan2,
    "logaddexp": jnp.logaddexp,
    "nextafter": jnp.nextafter,
    "copysign": jnp.copysign,
    "heaviside": jnp.heaviside,
    "hypot": jnp.hypot,
    "ldexp": jnp.ldexp,
}.items():
    register_op(_name)(_fn)


@register_op("pow")
def _pow(x, y):
    return jnp.power(x, y)


@register_op("divide_trunc")
def _divide_trunc(x, y):
    return jnp.trunc(jnp.divide(x, y)).astype(jnp.result_type(x, y))


# -- unary elementwise ------------------------------------------------------

for _name, _fn in {
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "log1p": jnp.log1p,
    "sqrt": jnp.sqrt,
    "rsqrt": lax.rsqrt,
    "abs": jnp.abs,
    "sign": jnp.sign,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "asin": jnp.arcsin,
    "acos": jnp.arccos,
    "atan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "asinh": jnp.arcsinh,
    "acosh": jnp.arccosh,
    "atanh": jnp.arctanh,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "round": jnp.round,
    "trunc": jnp.trunc,
    "frac": lambda x: x - jnp.trunc(x),
    "reciprocal": jnp.reciprocal,
    "square": jnp.square,
    "neg": jnp.negative,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "lgamma": jax.scipy.special.gammaln,
    "digamma": jax.scipy.special.digamma,
    "angle": jnp.angle,
    "conj": jnp.conj,
    "real": jnp.real,
    "imag": jnp.imag,
    "i0": jax.scipy.special.i0,
    "i1": jax.scipy.special.i1,
    "sigmoid": jax.nn.sigmoid,
    "logit_raw": jax.scipy.special.logit,
}.items():
    register_op(_name)(_fn)


@register_op("scale")
def _scale(x, scale=1.0, bias=0.0, bias_after_scale=True):
    # reference: phi/kernels/scale_kernel.h
    s = jnp.asarray(scale, x.dtype)
    b = jnp.asarray(bias, x.dtype)
    return x * s + b if bias_after_scale else (x + b) * s


@register_op("clip")
def _clip(x, min=None, max=None):
    return jnp.clip(x, min, max)


@register_op("logit")
def _logit(x, eps=None):
    if eps:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jax.scipy.special.logit(x)


@register_op("stanh")
def _stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


@register_op("rint")
def _rint(x):
    return jnp.rint(x)


# -- predicates (nondiff) ---------------------------------------------------

for _name, _fn in {
    "isnan": jnp.isnan,
    "isinf": jnp.isinf,
    "isfinite": jnp.isfinite,
    "equal": jnp.equal,
    "not_equal": jnp.not_equal,
    "greater_than": jnp.greater,
    "greater_equal": jnp.greater_equal,
    "less_than": jnp.less,
    "less_equal": jnp.less_equal,
    "logical_and": jnp.logical_and,
    "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
    "logical_not": jnp.logical_not,
    "bitwise_and": jnp.bitwise_and,
    "bitwise_or": jnp.bitwise_or,
    "bitwise_xor": jnp.bitwise_xor,
    "bitwise_not": jnp.bitwise_not,
    "signbit": jnp.signbit,
}.items():
    register_op(_name, nondiff=True)(_fn)


@register_op("isclose", nondiff=True)
def _isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@register_op("allclose", nondiff=True)
def _allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@register_op("equal_all", nondiff=True)
def _equal_all(x, y):
    return jnp.array_equal(x, y)


# -- reductions -------------------------------------------------------------

def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@register_op("sum")
def _sum(x, axis=None, keepdim=False, dtype=None):
    # paddle sums bool/int to int64 by default
    if dtype is None and jnp.issubdtype(x.dtype, jnp.bool_):
        dtype = jnp.int64
    return jnp.sum(x, axis=_norm_axis(axis), keepdims=keepdim, dtype=dtype)


@register_op("mean")
def _mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=_norm_axis(axis), keepdims=keepdim)


@register_op("max")
def _max(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_norm_axis(axis), keepdims=keepdim)


@register_op("min")
def _min(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_norm_axis(axis), keepdims=keepdim)


@register_op("prod")
def _prod(x, axis=None, keepdim=False, dtype=None):
    return jnp.prod(x, axis=_norm_axis(axis), keepdims=keepdim, dtype=dtype)


@register_op("amax")
def _amax(x, axis=None, keepdim=False):
    return jnp.amax(x, axis=_norm_axis(axis), keepdims=keepdim)


@register_op("amin")
def _amin(x, axis=None, keepdim=False):
    return jnp.amin(x, axis=_norm_axis(axis), keepdims=keepdim)


@register_op("nansum")
def _nansum(x, axis=None, keepdim=False, dtype=None):
    return jnp.nansum(x, axis=_norm_axis(axis), keepdims=keepdim, dtype=dtype)


@register_op("nanmean")
def _nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=_norm_axis(axis), keepdims=keepdim)


@register_op("all", nondiff=True)
def _all(x, axis=None, keepdim=False):
    return jnp.all(x, axis=_norm_axis(axis), keepdims=keepdim)


@register_op("any", nondiff=True)
def _any(x, axis=None, keepdim=False):
    return jnp.any(x, axis=_norm_axis(axis), keepdims=keepdim)


@register_op("argmax", nondiff=True)
def _argmax(x, axis=None, keepdim=False, dtype="int64"):
    r = jnp.argmax(x, axis=axis, keepdims=keepdim if axis is not None
                   else False)
    return r.astype(dtype)


@register_op("argmin", nondiff=True)
def _argmin(x, axis=None, keepdim=False, dtype="int64"):
    r = jnp.argmin(x, axis=axis, keepdims=keepdim if axis is not None
                   else False)
    return r.astype(dtype)


@register_op("logsumexp")
def _logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=_norm_axis(axis),
                                       keepdims=keepdim)


@register_op("cumsum")
def _cumsum(x, axis=None):
    if axis is None:
        return jnp.cumsum(x.reshape(-1))
    return jnp.cumsum(x, axis=int(axis))


@register_op("cumprod")
def _cumprod(x, dim=None):
    if dim is None:
        return jnp.cumprod(x.reshape(-1))
    return jnp.cumprod(x, axis=int(dim))


@register_op("cummax", nondiff=False)
def _cummax(x, axis=-1):
    vals = lax.associative_scan(jnp.maximum, x, axis=axis)
    return vals


@register_op("cummin")
def _cummin(x, axis=-1):
    return lax.associative_scan(jnp.minimum, x, axis=axis)


@register_op("median")
def _median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=_norm_axis(axis), keepdims=keepdim)


@register_op("quantile")
def _quantile(x, q, axis=None, keepdim=False):
    return jnp.quantile(x, jnp.asarray(q), axis=_norm_axis(axis),
                        keepdims=keepdim)


@register_op("count_nonzero", nondiff=True)
def _count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=_norm_axis(axis), keepdims=keepdim)


@register_op("var")
def _var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=_norm_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@register_op("std")
def _std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=_norm_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@register_op("kthvalue")
def _kthvalue(x, k, axis=-1, keepdim=False):
    idx = jnp.argsort(x, axis=axis)
    vals = jnp.take_along_axis(x, idx, axis=axis)
    taken = jnp.take(vals, k - 1, axis=axis)
    itaken = jnp.take(idx, k - 1, axis=axis).astype(jnp.int64)
    if keepdim:
        taken = jnp.expand_dims(taken, axis)
        itaken = jnp.expand_dims(itaken, axis)
    return taken, itaken


@register_op("trace_reduce")
def _trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)
