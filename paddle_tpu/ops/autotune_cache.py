"""Shape-class-keyed kernel-selection cache (r3 verdict item 9).

Reference analog: phi's autotune cache — algorithm choice memoised per
kernel+shape signature (paddle/phi/kernels/autotune/cache.h, switch_autotune.h:
N warmup steps measure candidates, the winner is cached and replayed).

TPU mapping: kernel choice here means WHICH lowering serves an op — the
Pallas kernel, the lax/XLA composite, or a streaming variant. The choice
must be static per jit trace, so selection happens at dispatch time
(ops/registry.py override predicates) via this cache:

- keys are SHAPE CLASSES — dims bucketed to powers of two — so one
  measurement covers a family of shapes, like the reference's cache
  keyed on (dims, dtype) tuples;
- entries persist per device kind under ``~/.cache/paddle_tpu/`` so a
  crossover measured once (e.g. by bench.py on real hardware) keeps
  serving later processes on the same chip generation;
- ``measure()`` times candidate thunks on concrete arrays (eager mode /
  warmup), stores the winner; ``choose()`` is the hot-path lookup with a
  heuristic default and hit/miss counters.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, Optional

from ..framework.monitor import stat_add, stat_observe
from ..profiler import span as _prof

__all__ = ["shape_class", "choose", "measure", "record", "stats",
           "clear", "cache_path", "set_device_kind"]

_lock = threading.Lock()
_entries: Dict[str, str] = {}
_loaded_for: Optional[str] = None
_device_kind: Optional[str] = None
_stats = {"hits": 0, "misses": 0, "measures": 0}


def _bucket(n: int) -> int:
    """Round up to a power of two — one cache entry per shape family."""
    if n <= 0:
        return 0
    return 1 << (int(n) - 1).bit_length()


def shape_class(*dims, **tags) -> str:
    """Canonical key fragment: pow2-bucketed dims + literal tags
    (dtype, causal flags, ...)."""
    parts = [str(_bucket(d)) if isinstance(d, int) else str(d)
             for d in dims]
    parts += [f"{k}={tags[k]}" for k in sorted(tags)]
    return "x".join(parts)


def set_device_kind(kind: Optional[str]) -> None:
    """Override the device-kind namespace (tests; pre-backend setup).
    ``None`` resets to autodetection from the jax backend."""
    global _device_kind, _loaded_for
    with _lock:
        _device_kind = kind
        _loaded_for = None


def _kind() -> str:
    global _device_kind
    if _device_kind is None:
        try:
            import jax
            _device_kind = jax.devices()[0].device_kind.replace(" ", "_")
        except Exception:
            _device_kind = "unknown"
    return _device_kind


def cache_path() -> str:
    # same per-user root as the persistent XLA compilation cache
    # (framework/compile_cache.py): one directory carries all
    # per-machine tuning state. PADDLE_AUTOTUNE_CACHE_DIR moves only
    # the autotune entries; PADDLE_TPU_CACHE_ROOT moves everything.
    from ..framework.compile_cache import cache_root
    root = os.environ.get("PADDLE_AUTOTUNE_CACHE_DIR", cache_root())
    return os.path.join(root, f"autotune_{_kind()}.json")


def _ensure_loaded() -> None:
    global _loaded_for
    kind = _kind()
    if _loaded_for == kind:
        return
    _entries.clear()
    try:
        with open(cache_path()) as f:
            _entries.update({str(k): str(v)
                             for k, v in json.load(f).items()})
    except (OSError, ValueError):
        pass
    _loaded_for = kind


def _persist() -> None:
    path = cache_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(_entries, f, indent=0, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        pass  # read-only home: cache stays in-process


def choose(op: str, key: str, default: str) -> str:
    """Hot-path lookup: the recorded winner for (op, shape class), or
    ``default`` (the heuristic crossover) when nothing is recorded."""
    with _lock:
        _ensure_loaded()
        got = _entries.get(f"{op}/{key}")
        if got is None:
            _stats["misses"] += 1
            stat_add("autotune_cache_miss")
            return default
        _stats["hits"] += 1
        stat_add("autotune_cache_hit")
        return got


def record(op: str, key: str, winner: str, persist: bool = True) -> None:
    with _lock:
        _ensure_loaded()
        _entries[f"{op}/{key}"] = winner
        if persist:
            _persist()


def measure(op: str, key: str, candidates: Dict[str, Callable],
            n_warmup: int = 1, n_iters: int = 3,
            persist: bool = True) -> str:
    """Time candidate thunks (must return device arrays; blocked on), store
    and return the winner. Call with CONCRETE inputs only — the reference's
    warmup-steps measurement, done explicitly rather than inside traces."""
    import jax
    t_measure = time.perf_counter()
    timings = {}
    with _prof.record(f"autotune_measure/{op}", "cache",
                      args={"key": key}):
        for name, thunk in candidates.items():
            try:
                for _ in range(n_warmup):
                    jax.block_until_ready(thunk())
                t0 = time.perf_counter()
                for _ in range(n_iters):
                    out = thunk()
                jax.block_until_ready(out)
                timings[name] = (time.perf_counter() - t0) / n_iters
            except Exception:
                continue  # a candidate that cannot run never wins
    # the measurement IS the compile+warmup cost the cache amortizes —
    # surface it so "how long did autotune take" has an answer
    stat_observe(f"autotune_measure_ms/{op}",
                 (time.perf_counter() - t_measure) * 1e3)
    if not timings:
        raise RuntimeError(f"no runnable candidate for {op}/{key}")
    winner = min(timings, key=timings.get)
    record(op, key, winner, persist=persist)
    with _lock:
        _stats["measures"] += 1
    return winner


def stats() -> dict:
    with _lock:
        out = dict(_stats)
        out["entries"] = len(_entries)
        return out


def clear(persist: bool = False) -> None:
    with _lock:
        _entries.clear()
        for k in _stats:
            _stats[k] = 0
        if persist:
            try:
                os.remove(cache_path())
            except OSError:
                pass
