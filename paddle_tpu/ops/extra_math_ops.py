"""Remaining tensor-op families: complex views, statistics, numeric
utilities, LU unpack, sharding helpers.

Reference analogs: paddle/phi/kernels/{lerp_kernel.h, dist_kernel.h,
logcumsumexp_kernel.h, mode_kernel.h, multiplex_kernel.h,
nanmedian_kernel.h, cholesky_solve_kernel.h, lu_unpack_kernel.h,
shard_index_kernel.h, complex_kernel.h} and python/paddle/tensor/math.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


@register_op("add_n")
def _add_n(inputs):
    out = inputs[0]
    for x in inputs[1:]:
        out = out + x
    return out


@register_op("lerp")
def _lerp(x, y, weight):
    return x + weight * (y - x)


@register_op("deg2rad")
def _deg2rad(x):
    return jnp.deg2rad(x)


@register_op("rad2deg")
def _rad2deg(x):
    return jnp.rad2deg(x)


@register_op("gcd", nondiff=True)
def _gcd(x, y):
    return jnp.gcd(x, y)


@register_op("lcm", nondiff=True)
def _lcm(x, y):
    return jnp.lcm(x, y)


@register_op("diff")
def _diff(x, prepend=None, append=None, n=1, axis=-1):
    return jnp.diff(x, n=n, axis=axis, prepend=prepend, append=append)


@register_op("dist")
def _dist(x, y, p=2.0):
    d = (x - y).ravel()
    p = float(p)
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == float("-inf"):
        return jnp.min(jnp.abs(d))
    if p == 0:
        return jnp.sum(d != 0).astype(x.dtype)
    return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)


@register_op("logcumsumexp")
def _logcumsumexp(x, axis=None):
    if axis is None:
        x = x.ravel()
        axis = 0
    return lax.cumlogsumexp(x, axis=axis)


@register_op("mode")
def _mode(x, axis=-1, keepdim=False):
    """Most frequent value along axis; ties resolve to the largest value
    (matching the reference's last-occurrence-in-sorted-order)."""
    ax = axis % x.ndim
    xs = jnp.moveaxis(x, ax, -1)
    sorted_x = jnp.sort(xs, axis=-1)
    n = sorted_x.shape[-1]
    # run length ending at each position
    same = jnp.concatenate(
        [jnp.zeros(sorted_x.shape[:-1] + (1,), bool),
         sorted_x[..., 1:] == sorted_x[..., :-1]], axis=-1)

    def scan_fn(carry, s):
        run = jnp.where(s, carry + 1, 1)
        return run, run

    _, runs = lax.scan(scan_fn,
                       jnp.ones(sorted_x.shape[:-1], jnp.int32),
                       jnp.moveaxis(same, -1, 0))
    runs = jnp.moveaxis(runs, 0, -1)
    # reference keeps the LAST max run (ties -> larger value): flip argmax
    rev_best = (n - 1) - jnp.argmax(runs[..., ::-1], axis=-1)
    values = jnp.take_along_axis(sorted_x, rev_best[..., None],
                                 axis=-1)[..., 0]
    # index of (last occurrence of) the mode in the ORIGINAL array
    eq = xs == values[..., None]
    idx = (n - 1) - jnp.argmax(eq[..., ::-1], axis=-1)
    if keepdim:
        values = jnp.expand_dims(values, ax)
        idx = jnp.expand_dims(idx, ax)
    return values, idx.astype(jnp.int64)


@register_op("multiplex")
def _multiplex(inputs, index):
    stacked = jnp.stack(inputs)                      # [K, N, ...]
    idx = index.reshape(-1).astype(jnp.int32)        # [N]
    rows = jnp.arange(stacked.shape[1])
    return stacked[idx, rows]


@register_op("nanmedian")
def _nanmedian(x, axis=None, keepdim=False):
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim).astype(x.dtype)


@register_op("nanquantile")
def _nanquantile(x, q, axis=None, keepdim=False):
    return jnp.nanquantile(x.astype(jnp.float64)
                           if x.dtype == jnp.float64 else
                           x.astype(jnp.float32),
                           jnp.asarray(q), axis=axis, keepdims=keepdim)


@register_op("cov")
def _cov(x, fweights=None, aweights=None, rowvar=True, ddof=True):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights).astype(x.dtype)


@register_op("corrcoef")
def _corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar).astype(x.dtype)


@register_op("lu_unpack")
def _lu_unpack(lu_mat, pivots, unpack_ludata=True, unpack_pivots=True):
    m, n = lu_mat.shape[-2], lu_mat.shape[-1]
    k = min(m, n)
    lower = jnp.tril(lu_mat[..., :, :k], k=-1)[..., :m, :]
    eye = jnp.eye(m, k, dtype=lu_mat.dtype)
    l_mat = lower + eye
    u_mat = jnp.triu(lu_mat)[..., :k, :]
    # pivots (1-based sequential row swaps, LAPACK ipiv) -> permutation
    piv = pivots.astype(jnp.int32) - 1

    def perm_from_ipiv(ip):
        perm = jnp.arange(m)

        def body(i, p):
            j = ip[i]
            pi = p[i]
            pj = p[j]
            p = p.at[i].set(pj).at[j].set(pi)
            return p

        perm = lax.fori_loop(0, ip.shape[0], body, perm)
        return perm

    batch = piv.shape[:-1]
    if batch:
        perm = jax.vmap(perm_from_ipiv)(piv.reshape(-1, piv.shape[-1]))
        perm = perm.reshape(batch + (m,))
    else:
        perm = perm_from_ipiv(piv)
    p_mat = jax.nn.one_hot(perm, m, dtype=lu_mat.dtype)
    # rows of P: P[perm[i], i] = 1 so that A = P L U
    p_mat = jnp.swapaxes(p_mat, -1, -2)
    return p_mat, l_mat, u_mat


@register_op("shard_index", nondiff=True)
def _shard_index(x, index_num, nshards, shard_id, ignore_value=-1):
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    return jnp.where(in_shard, x % shard_size, ignore_value)


@register_op("as_complex")
def _as_complex(x):
    return lax.complex(x[..., 0], x[..., 1])


@register_op("as_real")
def _as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@register_op("make_complex")
def _make_complex(real, imag):
    return lax.complex(real, imag)


@register_op("randint_like", nondiff=True)
def _randint_like(x, key, low=0, high=None):
    if high is None:
        low, high = 0, low
    return jax.random.randint(key, x.shape, int(low), int(high),
                              dtype=jnp.int64)
