"""Variable-length sequence ops — the TPU-native LoD replacement.

The reference models ragged batches with LoDTensor
(/root/reference/paddle/fluid/framework/lod_tensor.h:1): one flat value
tensor plus level-of-detail offsets, and a family of sequence ops that
walk those offsets per sequence
(/root/reference/paddle/fluid/operators/sequence_ops/sequence_pad_op.cc:1
and pool/expand/softmax/conv/reverse/slice siblings).

Offset-walking scalar loops don't map to the MXU, and dynamic per-batch
shapes defeat XLA compilation. The TPU-native encoding is therefore:

  * a DENSE padded tensor   x : (batch, maxlen, ...)   — static maxlen
  * a lengths vector        lengths : (batch,) int32/int64

Every op here consumes/produces that pair with masking, so the whole
family jit-compiles to fused vector code with no data-dependent shapes.
``sequence_pad``/``sequence_unpad`` convert between the reference's flat
(packed) encoding and the dense one; the DataLoader's bucketing sampler
(io.BucketedBatchSampler) bounds the padding waste by grouping samples
of similar length, quantizing maxlen to a few bucket boundaries so each
bucket compiles once (SURVEY.md §7 "hard parts": padding/bucketing baked
into the DataLoader).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register_op

_NEG_INF = -1e30


def _mask2d(lengths, maxlen):
    """(batch, maxlen) validity mask from a lengths vector."""
    r = jnp.arange(maxlen)
    return r[None, :] < lengths.reshape(-1, 1)


def _expand_mask(mask, x):
    """Broadcast a (batch, maxlen) mask over x's trailing feature dims."""
    return mask.reshape(mask.shape + (1,) * (x.ndim - 2))


def _offsets(lengths):
    """Exclusive cumsum: start offset of each sequence in the packed
    layout (the analog of the reference's LoD level-0 offsets)."""
    return jnp.concatenate(
        [jnp.zeros((1,), lengths.dtype), jnp.cumsum(lengths)[:-1]])


# ---------------------------------------------------------------------------
# pack <-> pad conversion
# ---------------------------------------------------------------------------

@register_op("sequence_pad")
def _sequence_pad(flat, lengths, pad_value=0.0, maxlen=None):
    """Packed (total, ...) + lengths -> dense (batch, maxlen, ...).

    Reference: sequence_ops/sequence_pad_op.cc:1 (LoDTensor -> padded).
    ``maxlen`` must be static (jit); positions past each length hold
    ``pad_value``. A pure gather: out[b, t] = flat[off[b] + t].
    """
    if maxlen is None:
        raise ValueError("sequence_pad: maxlen must be a static int "
                         "(dynamic output shapes cannot compile)")
    m = int(maxlen)
    idx = _offsets(lengths)[:, None] + jnp.arange(m)[None, :]
    idx = jnp.clip(idx, 0, flat.shape[0] - 1)
    out = jnp.take(flat, idx.reshape(-1), axis=0).reshape(
        (lengths.shape[0], m) + flat.shape[1:])
    mask = _expand_mask(_mask2d(lengths, m), out)
    return jnp.where(mask, out, jnp.asarray(pad_value, out.dtype))


@register_op("sequence_unpad")
def _sequence_unpad(x, lengths, total_length=None):
    """Dense (batch, maxlen, ...) -> packed (total_length, ...).

    Reference: sequence_ops/sequence_unpad_op.cc. ``total_length`` must
    be static under jit; rows past sum(lengths) are zero-filled. The
    packed row i lives at (b, t) with b = searchsorted(ends, i) and
    t = i - off[b].
    """
    batch, maxlen = x.shape[0], x.shape[1]
    total = int(total_length) if total_length is not None \
        else batch * maxlen
    ends = jnp.cumsum(lengths)
    i = jnp.arange(total)
    b = jnp.searchsorted(ends, i, side="right")
    b = jnp.clip(b, 0, batch - 1)
    t = i - _offsets(lengths)[b]
    valid = i < ends[-1]
    t = jnp.clip(t, 0, maxlen - 1)
    out = x[b, t]
    vm = valid.reshape((total,) + (1,) * (x.ndim - 2))
    return jnp.where(vm, out, jnp.zeros((), x.dtype))


# ---------------------------------------------------------------------------
# masked reductions / normalization
# ---------------------------------------------------------------------------

@register_op("sequence_pool")
def _sequence_pool(x, lengths, pool_type="sum"):
    """Per-sequence reduction over the time axis.

    Reference: sequence_ops/sequence_pool_op.cc (SUM/MEAN/MAX/MIN/
    SQRT/FIRST/LAST over each LoD span) — here a masked reduce over
    axis 1 of the dense layout.
    """
    pt = pool_type.lower()
    maxlen = x.shape[1]
    mask = _expand_mask(_mask2d(lengths, maxlen), x)
    ln = jnp.maximum(lengths, 1).astype(
        x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32)
    ln = ln.reshape((-1,) + (1,) * (x.ndim - 2))
    def _extreme(largest):
        # identity element for max/min; computed only in those branches so
        # sum/mean on bool (where iinfo is undefined) still works
        if jnp.issubdtype(x.dtype, jnp.floating):
            v = _NEG_INF if largest else -_NEG_INF
        elif x.dtype == jnp.bool_:
            v = not largest
        else:  # keep integer dtypes integer (no silent float64 promotion)
            info = jnp.iinfo(x.dtype)
            v = info.min if largest else info.max
        return jnp.asarray(v, x.dtype)

    if pt == "sum":
        return jnp.where(mask, x, 0).sum(axis=1)
    if pt == "average" or pt == "mean":
        return jnp.where(mask, x, 0).sum(axis=1) / ln
    if pt == "sqrt":
        return jnp.where(mask, x, 0).sum(axis=1) / jnp.sqrt(ln)
    if pt == "max":
        return jnp.where(mask, x, _extreme(True)).max(axis=1)
    if pt == "min":
        return jnp.where(mask, x, _extreme(False)).min(axis=1)
    if pt == "first":
        return x[:, 0]
    if pt == "last":
        idx = jnp.maximum(lengths - 1, 0)
        return jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)).astype(jnp.int32),
            axis=1).squeeze(1)
    raise ValueError(f"sequence_pool: unknown pool_type {pool_type!r}")


@register_op("sequence_softmax")
def _sequence_softmax(x, lengths):
    """Masked softmax over the time axis (axis 1); padded positions get
    probability 0. Reference: sequence_ops/sequence_softmax_op.cc."""
    mask = _expand_mask(_mask2d(lengths, x.shape[1]), x)
    logits = jnp.where(mask, x, _NEG_INF)
    m = logits.max(axis=1, keepdims=True)
    e = jnp.exp(logits - lax.stop_gradient(m))
    e = jnp.where(mask, e, 0)
    return e / jnp.maximum(e.sum(axis=1, keepdims=True), 1e-30)


# ---------------------------------------------------------------------------
# reordering / expansion
# ---------------------------------------------------------------------------

@register_op("sequence_reverse")
def _sequence_reverse(x, lengths):
    """Reverse each valid prefix; padding stays in place.
    Reference: sequence_ops/sequence_reverse_op.h."""
    maxlen = x.shape[1]
    t = jnp.arange(maxlen)[None, :]
    ln = lengths.reshape(-1, 1)
    src = jnp.where(t < ln, ln - 1 - t, t).astype(jnp.int32)
    return jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)


@register_op("sequence_expand")
def _sequence_expand(x, ref_lengths, maxlen=None):
    """Broadcast per-sequence features across timesteps: (batch, d...) ->
    (batch, maxlen, d...), valid for t < ref_lengths[b], zero after.

    Reference: sequence_ops/sequence_expand_op.cc — the common case
    (expand a one-step sequence to the length of a reference sequence).
    The general two-level-LoD form collapses to this under the dense
    encoding.
    """
    if maxlen is None:
        raise ValueError("sequence_expand: maxlen must be a static int")
    m = int(maxlen)
    out = jnp.broadcast_to(
        x[:, None], (x.shape[0], m) + x.shape[1:])
    mask = _expand_mask(_mask2d(ref_lengths, m), out)
    return jnp.where(mask, out, jnp.zeros((), x.dtype))


@register_op("sequence_slice")
def _sequence_slice(x, lengths, offset, length, maxlen=None):
    """Per-sequence slice: out[b, t] = x[b, offset[b] + t] for
    t < length[b]. Reference: sequence_ops/sequence_slice_op.h aborts
    when offset+length exceeds the sequence; data-dependent aborts can't
    compile, so the jit-safe analog TRUNCATES the slice at each
    sequence's valid end (no padding rows ever leak into the output).
    The output time axis is ``maxlen`` (static; default: input maxlen)."""
    m = int(maxlen) if maxlen is not None else x.shape[1]
    off = jnp.asarray(offset).reshape(-1, 1)
    ln = jnp.asarray(length).reshape(-1, 1)
    seq_ln = jnp.asarray(lengths).reshape(-1, 1)
    # clamp: a slice may not extend past the sequence's valid prefix
    eff = jnp.clip(jnp.minimum(ln, seq_ln - off), 0)
    t = jnp.arange(m)[None, :]
    src = jnp.clip(off + t, 0, x.shape[1] - 1).astype(jnp.int32)
    out = jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)
    mask = _expand_mask(t < eff, out)
    return jnp.where(mask, out, jnp.zeros((), x.dtype))


@register_op("sequence_enumerate", nondiff=True)
def _sequence_enumerate(ids, lengths, win_size, pad_value=0):
    """Sliding windows of token ids: (batch, maxlen) int ->
    (batch, maxlen, win_size); window positions past the sequence end
    (or window cells past it) hold ``pad_value``.
    Reference: sequence_ops/sequence_enumerate_op.cc."""
    maxlen = ids.shape[1]
    w = int(win_size)
    t = jnp.arange(maxlen)[:, None] + jnp.arange(w)[None, :]  # (T, W)
    src = jnp.clip(t, 0, maxlen - 1)
    out = ids[:, src]  # (B, T, W)
    ln = lengths.reshape(-1, 1, 1)
    valid = t[None] < ln
    return jnp.where(valid, out, jnp.asarray(pad_value, ids.dtype))


# ---------------------------------------------------------------------------
# sequence conv — context-window projection (an MXU-friendly matmul)
# ---------------------------------------------------------------------------

@register_op("sequence_conv")
def _sequence_conv(x, lengths, weight, bias=None, context_length=3,
                   context_start=None, pad_value=0.0):
    """Context-window convolution over each sequence.

    Reference: sequence_ops/sequence_conv_op.cc — im2col over each LoD
    span then GEMM with a (context_length*d, out) filter. Dense version:
    zero the padding, stack ``context_length`` shifted copies along the
    feature axis, one matmul. Timesteps outside a sequence contribute
    ``pad_value`` exactly as the reference's sequence-boundary padding.

    x: (batch, maxlen, d_in); weight: (context_length * d_in, d_out).
    """
    cl = int(context_length)
    cs = int(context_start) if context_start is not None else -(cl // 2)
    mask = _expand_mask(_mask2d(lengths, x.shape[1]), x)
    xz = jnp.where(mask, x, jnp.asarray(pad_value, x.dtype))
    cols = []
    for k in range(cl):
        shift = cs + k
        rolled = jnp.roll(xz, -shift, axis=1)
        t = jnp.arange(x.shape[1])
        inside = (t + shift >= 0) & (t + shift < x.shape[1])
        rolled = jnp.where(
            inside.reshape((1, -1) + (1,) * (x.ndim - 2)), rolled,
            jnp.asarray(pad_value, x.dtype))
        cols.append(rolled)
    stacked = jnp.concatenate(cols, axis=-1)  # (B, T, cl*d)
    out = jnp.einsum("btd,do->bto", stacked, weight,
                     preferred_element_type=jnp.float32).astype(x.dtype)
    if bias is not None:
        out = out + bias
    # padded output positions are zeroed (they carry no sequence data)
    omask = _expand_mask(_mask2d(lengths, x.shape[1]), out)
    return jnp.where(omask, out, jnp.zeros((), out.dtype))


@register_op("sequence_concat")
def _sequence_concat(xs, lengths_list, maxlen=None):
    """Concatenate sequences element-wise across inputs: output sequence
    b = concat(x1[b][:l1[b]], x2[b][:l2[b]], ...). Reference:
    sequence_ops/sequence_concat_op.cc. Returns (padded, total_lengths).
    ``maxlen`` static; default sum of input maxlens."""
    m = int(maxlen) if maxlen is not None else sum(x.shape[1] for x in xs)
    total_len = sum(lengths_list)
    batch = xs[0].shape[0]
    # build by scattering each input at its running offset
    out = jnp.zeros((batch, m) + xs[0].shape[2:], xs[0].dtype)
    t = jnp.arange(m)[None, :]
    running = jnp.zeros((batch, 1), lengths_list[0].dtype)
    for x, ln in zip(xs, lengths_list):
        lnc = ln.reshape(-1, 1)
        # position t in out takes x[b, t - running[b]] when
        # running <= t < running + ln
        src = jnp.clip(t - running, 0, x.shape[1] - 1).astype(jnp.int32)
        gathered = jnp.take_along_axis(
            x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)
        sel = (t >= running) & (t < running + lnc)
        out = jnp.where(_expand_mask(sel, out), gathered, out)
        running = running + lnc
    return out, total_len


@register_op("row_conv")
def _row_conv(x, weight):
    """Lookahead row convolution (reference row_conv op, DeepSpeech2):
    out[t] = sum_{i=0..k-1} weight[i] * x[t+i], zero-padded tail."""
    k, d = weight.shape
    t = x.shape[-2]
    pad = jnp.zeros(x.shape[:-2] + (k - 1, d), x.dtype)
    xp = jnp.concatenate([x, pad], axis=-2)
    out = jnp.zeros_like(x)
    for i in range(k):     # k is small and static: unrolled adds fuse
        out = out + xp[..., i:i + t, :] * weight[i]
    return out


@register_op("sequence_scatter")
def _sequence_scatter(x, index, updates):
    """Add ``updates`` at per-row time positions ``index`` (reference
    sequence_scatter_op.cc, dense [B, T, ...] form)."""
    rows = jnp.arange(x.shape[0])[:, None]
    return x.at[rows, index.astype(jnp.int32)].add(updates)


@register_op("nce_loss")
def _nce_loss(x, label, weight, bias, neg_samples):
    """Noise-contrastive estimation loss (reference nce op): logistic
    loss over the true class + the given negative sample ids."""
    import jax
    lab = label.reshape(-1).astype(jnp.int32)
    pos_logit = (x * weight[lab]).sum(-1) + bias[lab]
    neg = neg_samples.astype(jnp.int32)
    neg_logit = jnp.einsum("bd,bkd->bk", x, weight[neg]) + bias[neg]
    loss = jax.nn.softplus(-pos_logit) + jax.nn.softplus(neg_logit).sum(-1)
    return loss.reshape(-1, 1)
