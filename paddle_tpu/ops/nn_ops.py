"""Neural-net op implementations: conv, pooling, normalization, embedding,
activations, losses, attention.

Analog of the reference's phi nn kernels (/root/reference/paddle/phi/kernels/
conv_kernel.h, pool_kernel.h, batch_norm_kernel.h, layer_norm_kernel.h,
embedding_kernel.h, softmax_kernel.h, cross_entropy_kernel.h) and the fused
CUDA training kernels (paddle/fluid/operators/fused/). On TPU the "fusion" is
XLA's job; convs map to ``lax.conv_general_dilated`` on the MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

# -- activations ------------------------------------------------------------

for _name, _fn in {
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "softplus_raw": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    "hardswish": jax.nn.hard_swish,
    "tanhshrink": lambda x: x - jnp.tanh(x),
    "log_sigmoid": jax.nn.log_sigmoid,
}.items():
    register_op(_name)(_fn)


@register_op("alpha_dropout")
def _alpha_dropout(x, key, p=0.5):
    # SELU-preserving dropout (reference: nn/functional/common.py
    # alpha_dropout): dropped units take alpha' and the output is affinely
    # rescaled so mean/variance are preserved.
    alpha = 1.6732632423543772 * 1.0507009873554805
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    a = (1.0 / ((1.0 - p) * (1.0 + p * alpha ** 2)) ** 0.5)
    b = -a * alpha * p
    return (jnp.where(keep, x, -alpha) * a + b).astype(x.dtype)


@register_op("gelu")
def _gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=bool(approximate))


@register_op("leaky_relu")
def _leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope)


@register_op("elu")
def _elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


@register_op("celu")
def _celu(x, alpha=1.0):
    return jax.nn.celu(x, alpha)


@register_op("selu")
def _selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@register_op("hardsigmoid")
def _hardsigmoid(x, slope=0.1666667, offset=0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


@register_op("hardtanh")
def _hardtanh(x, min=-1.0, max=1.0):
    return jnp.clip(x, min, max)


@register_op("hardshrink")
def _hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0).astype(x.dtype)


@register_op("softshrink")
def _softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0)
                     ).astype(x.dtype)


@register_op("softplus")
def _softplus(x, beta=1.0, threshold=20.0):
    bx = beta * x
    return jnp.where(bx > threshold, x, jax.nn.softplus(bx) / beta)


@register_op("thresholded_relu")
def _thresholded_relu(x, threshold=1.0):
    return jnp.where(x > threshold, x, 0.0).astype(x.dtype)


@register_op("prelu")
def _prelu(x, alpha):
    a = alpha
    if a.ndim == 1 and x.ndim > 1 and a.shape[0] == x.shape[1]:
        a = a.reshape((1, -1) + (1,) * (x.ndim - 2))
    return jnp.where(x > 0, x, a * x)


@register_op("rrelu")
def _rrelu(x, key, lower=0.125, upper=0.333333, training=True):
    if training:
        a = jax.random.uniform(key, x.shape, x.dtype, lower, upper)
    else:
        a = jnp.asarray((lower + upper) / 2, x.dtype)
    return jnp.where(x >= 0, x, a * x)


@register_op("softmax")
def _softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=int(axis))


@register_op("log_softmax")
def _log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=int(axis))


@register_op("gumbel_softmax")
def _gumbel_softmax(x, key, temperature=1.0, hard=False, axis=-1):
    g = jax.random.gumbel(key, x.shape, x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        hard_y = jnp.zeros_like(y)
        hard_y = jnp.put_along_axis(hard_y, idx, 1.0, axis=axis,
                                    inplace=False) \
            if hasattr(jnp, "put_along_axis") else \
            hard_y.at[_axis_idx(idx, axis, y.shape)].set(1.0)
        y = lax.stop_gradient(hard_y - y) + y
    return y


def _axis_idx(idx, axis, shape):
    nd = len(shape)
    axis = axis % nd
    return tuple(
        idx.squeeze(axis) if d == axis else
        jnp.broadcast_to(
            jnp.arange(shape[d]).reshape(
                tuple(-1 if i == d else 1 for i in range(nd) if i != axis)),
            idx.squeeze(axis).shape)
        for d in range(nd))


@register_op("maxout")
def _maxout(x, groups, axis=1):
    axis = axis % x.ndim
    c = x.shape[axis]
    new_shape = x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1:]
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


@register_op("glu")
def _glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


# -- linear / embedding -----------------------------------------------------

@register_op("spectral_norm", nondiff=True)
def _spectral_norm(weight, u, v, dim=0, power_iters=1, eps=1e-12):
    w = jnp.moveaxis(weight, dim, 0).reshape(weight.shape[dim], -1)
    for _ in range(max(1, int(power_iters))):
        v = w.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = w @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ w @ v
    return weight / sigma


@register_op("bilinear")
def _bilinear(x1, x2, weight, bias=None):
    out = jnp.einsum("bi,kij,bj->bk", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


@register_op("linear")
def _linear(x, w, b=None):
    acc = jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else None
    out = jnp.matmul(x, w, preferred_element_type=acc)
    if acc is not None:
        out = out.astype(x.dtype)
    if b is not None:
        out = out + b
    return out


@register_op("embedding")
def _embedding(ids, weight, padding_idx=None):
    out = jnp.take(weight, ids, axis=0)
    if padding_idx is not None:
        if padding_idx < 0:  # negative counts back from vocab size
            padding_idx += weight.shape[0]
        mask = (ids == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out).astype(weight.dtype)
    return out


# -- conv / pool ------------------------------------------------------------

def _conv_dims(nd, data_format):
    # the WEIGHT is always OIHW-family (reference layout, independent of
    # data_format); only activations change layout. XLA accepts mixed
    # specs like ("NHWC", "OIHW", "NHWC") directly.
    if data_format in ("NCHW", "NCL", "NCDHW"):
        spec = ("NCHW", "OIHW", "NCHW") if nd == 2 else \
               (("NCH", "OIH", "NCH") if nd == 1 else
                ("NCDHW", "OIDHW", "NCDHW"))
    else:
        spec = ("NHWC", "OIHW", "NHWC") if nd == 2 else \
               (("NHC", "OIH", "NHC") if nd == 1 else
                ("NDHWC", "OIDHW", "NDHWC"))
    return spec


def _norm_tuple(v, nd):
    if isinstance(v, int):
        return (v,) * nd
    return tuple(v)


def _conv_padding(padding, nd):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if len(padding) == nd and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * nd:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(nd)]
    return [tuple(p) for p in padding]


@register_op("conv2d")
def _conv2d(x, w, bias=None, stride=1, padding=0, dilation=1, groups=1,
            data_format="NCHW"):
    return _convnd(x, w, bias, stride, padding, dilation, groups,
                   data_format, nd=2)


@register_op("conv1d")
def _conv1d(x, w, bias=None, stride=1, padding=0, dilation=1, groups=1,
            data_format="NCL"):
    return _convnd(x, w, bias, stride, padding, dilation, groups,
                   data_format, nd=1)


@register_op("conv3d")
def _conv3d(x, w, bias=None, stride=1, padding=0, dilation=1, groups=1,
            data_format="NCDHW"):
    return _convnd(x, w, bias, stride, padding, dilation, groups,
                   data_format, nd=3)


def _convnd(x, w, bias, stride, padding, dilation, groups, data_format, nd):
    lhs_spec, rhs_spec, out_spec = _conv_dims(nd, data_format)
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    (lhs_spec, rhs_spec, out_spec))
    # No preferred_element_type here: the MXU accumulates bf16 convs in f32
    # regardless, and jax's conv transpose rule rejects the mixed-dtype grad
    # conv that an f32-output/bf16-input conv produces. fp16 (narrow
    # exponent, real overflow risk in the reduction) computes via f32
    # casts instead — the cast primitives carry well-defined transposes.
    fp16 = x.dtype == jnp.float16
    if fp16:
        x = x.astype(jnp.float32)
        w = w.astype(jnp.float32)
    out = lax.conv_general_dilated(
        x, w,
        window_strides=_norm_tuple(stride, nd),
        padding=_conv_padding(padding, nd),
        rhs_dilation=_norm_tuple(dilation, nd),
        dimension_numbers=dn,
        feature_group_count=int(groups))
    if fp16:
        out = out.astype(jnp.float16)
    if bias is not None:
        if data_format.startswith("NC"):
            out = out + bias.reshape((1, -1) + (1,) * nd)
        else:
            out = out + bias
    return out


def _transpose_str_pads(s, in_sizes, ksizes, strides):
    """Explicit pads for conv_transpose string padding, matching the
    reference's UpdatePaddingAndDilation (phi/kernels/cpu/conv_util.h:50):
    VALID = no pad; SAME computes per-dim
    pad_sum = max((ceil(in/stride)-1)*stride + k - in, 0) from the INPUT
    size, split left-light. The caller must also force dilation to 1
    under SAME, as the reference does."""
    if s.upper() == "VALID":
        return [(0, 0)] * len(ksizes)
    pads = []
    for L, k, st in zip(in_sizes, ksizes, strides):
        pt = max((-(-L // st) - 1) * st + k - L, 0)
        pads.append((pt // 2, pt - pt // 2))
    return pads


@register_op("conv2d_transpose")
def _conv2d_transpose(x, w, bias=None, stride=1, padding=0,
                      output_padding=0, dilation=1, groups=1,
                      data_format="NCHW", output_size=None):
    nd = 2
    strides = _norm_tuple(stride, nd)
    pads = _conv_padding(padding, nd)
    dil = _norm_tuple(dilation, nd)
    opad = _norm_tuple(output_padding, nd)
    if isinstance(pads, str):
        spatial = x.shape[2:2 + nd] if data_format == "NCHW" \
            else x.shape[1:1 + nd]
        if pads.upper() == "SAME":
            dil = (1,) * nd  # reference forces dilation=1 under SAME
        pads = _transpose_str_pads(pads, spatial, w.shape[2:], strides)
    # w layout: (in, out/groups, kh, kw) in paddle
    lhs_spec = "NCHW" if data_format == "NCHW" else "NHWC"
    if groups != 1:
        # grouped transpose conv via per-group slicing
        xs = jnp.split(x, groups, axis=1 if data_format == "NCHW" else -1)
        ws = jnp.split(w, groups, axis=0)
        outs = [_conv2d_transpose(xg, wg, None, stride, padding,
                                  output_padding, dilation, 1, data_format)
                for xg, wg in zip(xs, ws)]
        out = jnp.concatenate(outs, axis=1 if data_format == "NCHW" else -1)
    else:
        dn = lax.conv_dimension_numbers(
            x.shape, (w.shape[1], w.shape[0], w.shape[2], w.shape[3]),
            (lhs_spec, "OIHW", lhs_spec))
        # transpose conv = gradient of conv: use conv_transpose
        pad_trans = [
            (d * (k - 1) - p0, d * (k - 1) - p1 + op)
            for (p0, p1), k, d, op in zip(pads, w.shape[2:], dil, opad)]
        out = lax.conv_general_dilated(
            x, jnp.swapaxes(w, 0, 1)[:, :, ::-1, ::-1],
            window_strides=(1, 1),
            padding=pad_trans,
            lhs_dilation=strides,
            rhs_dilation=dil,
            dimension_numbers=dn)
    if bias is not None:
        out = out + (bias.reshape(1, -1, 1, 1) if data_format == "NCHW"
                     else bias)
    return out


def _pool(x, ksize, stride, padding, nd, data_format, mode,
          ceil_mode=False, exclusive=True):
    ksize = _norm_tuple(ksize, nd)
    stride = _norm_tuple(stride if stride is not None else ksize, nd)
    pads = _conv_padding(padding, nd)
    if ceil_mode and not isinstance(pads, str):
        # Extend each spatial dim's right padding so a trailing partial
        # window produces one more output position: out = ceil((L+p0+p1-k)/s)+1.
        # The extra pad region holds the reduce_window init value (-inf / 0),
        # so it never contaminates max results or exclusive-avg counts.
        spatial = x.shape[2:2 + nd] if data_format.startswith("NC") \
            else x.shape[1:1 + nd]
        pads = [(p0, p1 + (-(L + p0 + p1 - k)) % s)
                for (p0, p1), L, k, s in zip(pads, spatial, ksize, stride)]
    if data_format.startswith("NC"):
        window = (1, 1) + ksize
        strides = (1, 1) + stride
        pad_all = [(0, 0), (0, 0)] + (pads if not isinstance(pads, str)
                                      else pads)
    else:
        window = (1,) + ksize + (1,)
        strides = (1,) + stride + (1,)
        pad_all = [(0, 0)] + (pads if not isinstance(pads, str) else pads) \
            + [(0, 0)]
    if isinstance(pads, str):
        pad_all = pads
    if mode == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides, pad_all)
    # avg
    ones = jnp.ones_like(x)
    s = lax.reduce_window(x, 0.0, lax.add, window, strides, pad_all)
    if exclusive:
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pad_all)
    else:
        cnt = jnp.asarray(float(jnp.prod(jnp.asarray(ksize))), x.dtype)
    return s / cnt


@register_op("max_pool2d")
def _max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
                data_format="NCHW"):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "max",
                 ceil_mode)


@register_op("avg_pool2d")
def _avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
                exclusive=True, data_format="NCHW"):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "avg",
                 ceil_mode, exclusive)


@register_op("max_pool1d")
def _max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False):
    return _pool(x, kernel_size, stride, padding, 1, "NCL", "max", ceil_mode)


@register_op("avg_pool1d")
def _avg_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
                exclusive=True):
    return _pool(x, kernel_size, stride, padding, 1, "NCL", "avg",
                 ceil_mode, exclusive)


@register_op("max_pool3d")
def _max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
                data_format="NCDHW"):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "max",
                 ceil_mode)


@register_op("avg_pool3d")
def _avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
                exclusive=True, data_format="NCDHW"):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "avg",
                 ceil_mode, exclusive)


@register_op("adaptive_avg_pool2d")
def _adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    return _adaptive_pool(x, output_size, 2, data_format, "avg")


@register_op("adaptive_max_pool2d")
def _adaptive_max_pool2d(x, output_size, data_format="NCHW"):
    return _adaptive_pool(x, output_size, 2, data_format, "max")


@register_op("adaptive_avg_pool1d")
def _adaptive_avg_pool1d(x, output_size):
    return _adaptive_pool(x, output_size, 1, "NCL", "avg")


@register_op("adaptive_max_pool1d")
def _adaptive_max_pool1d(x, output_size):
    return _adaptive_pool(x, output_size, 1, "NCL", "max")


def _adaptive_pool(x, output_size, nd, data_format, mode):
    out_sizes = _norm_tuple(output_size, nd)
    spatial_off = 2 if data_format.startswith("NC") else 1
    out = x
    for d in range(nd):
        axis = spatial_off + d
        in_s = out.shape[axis]
        out_s = out_sizes[d] if out_sizes[d] is not None else in_s
        if in_s % out_s == 0:
            k = in_s // out_s
            shape = (out.shape[:axis] + (out_s, k) + out.shape[axis + 1:])
            r = out.reshape(shape)
            out = jnp.max(r, axis=axis + 1) if mode == "max" else \
                jnp.mean(r, axis=axis + 1)
        else:
            # generic: per-output-window segments (torch/paddle formula)
            starts = (jnp.arange(out_s) * in_s) // out_s
            ends = ((jnp.arange(out_s) + 1) * in_s + out_s - 1) // out_s
            idx = jnp.arange(in_s)
            mask = (idx[None, :] >= starts[:, None]) & \
                   (idx[None, :] < ends[:, None])
            moved = jnp.moveaxis(out, axis, -1)
            if mode == "max":
                seg = jnp.where(mask[(None,) * (moved.ndim - 1)],
                                moved[..., None, :], -jnp.inf)
                res = jnp.max(seg, axis=-1)
            else:
                w = mask.astype(out.dtype)
                res = jnp.einsum("...i,oi->...o", moved, w) / \
                    jnp.sum(w, axis=1)
            out = jnp.moveaxis(res, -1, axis)
    return out


# -- normalization ----------------------------------------------------------

@register_op("layer_norm")
def _layer_norm(x, weight=None, bias=None, epsilon=1e-5,
                begin_norm_axis=None):
    axes = tuple(range(begin_norm_axis if begin_norm_axis is not None
                       else x.ndim - 1, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    out = (xf - mean) * lax.rsqrt(var + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


@register_op("batch_norm")
def _batch_norm(x, running_mean, running_var, weight=None, bias=None,
                training=False, momentum=0.9, epsilon=1e-5,
                data_format="NCHW"):
    """Returns (y, new_mean, new_var) — buffer updates are explicit outputs
    (functional analog of the reference's in-place running stats,
    phi/kernels/batch_norm_kernel.h)."""
    c_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = tuple(-1 if i == c_axis else 1 for i in range(x.ndim))
    xf = x.astype(jnp.float32)
    if training:
        mean = jnp.mean(xf, axis=axes)
        var = jnp.var(xf, axis=axes)
        n = x.size // x.shape[c_axis]
        unbiased = var * (n / max(n - 1, 1))
        new_mean = momentum * running_mean + (1 - momentum) * mean
        new_var = momentum * running_var + (1 - momentum) * unbiased
    else:
        mean, var = running_mean.astype(jnp.float32), \
            running_var.astype(jnp.float32)
        new_mean, new_var = running_mean, running_var
    out = (xf - mean.reshape(bshape)) * lax.rsqrt(
        var.reshape(bshape) + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight.reshape(bshape)
    if bias is not None:
        out = out + bias.reshape(bshape)
    return out, new_mean.astype(running_mean.dtype), \
        new_var.astype(running_var.dtype)


@register_op("instance_norm")
def _instance_norm(x, weight=None, bias=None, epsilon=1e-5):
    axes = tuple(range(2, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = ((xf - mean) * lax.rsqrt(var + epsilon)).astype(x.dtype)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(bshape)
    if bias is not None:
        out = out + bias.reshape(bshape)
    return out


@register_op("group_norm")
def _group_norm(x, weight=None, bias=None, epsilon=1e-5, num_groups=1,
                data_format="NCHW"):
    if not data_format.startswith("NC"):
        x_t = jnp.moveaxis(x, -1, 1)
        out = _group_norm(x_t, weight, bias, epsilon, num_groups, "NCHW")
        return jnp.moveaxis(out, 1, -1)
    n, c = x.shape[0], x.shape[1]
    g = int(num_groups)
    xf = x.astype(jnp.float32).reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xf.ndim))
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = ((xf - mean) * lax.rsqrt(var + epsilon)).reshape(x.shape) \
        .astype(x.dtype)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(bshape)
    if bias is not None:
        out = out + bias.reshape(bshape)
    return out


@register_op("rms_norm")
def _rms_norm(x, weight=None, epsilon=1e-6):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = (xf * lax.rsqrt(ms + epsilon)).astype(x.dtype)
    if weight is not None:
        out = out * weight
    return out


@register_op("local_response_norm")
def _lrn(x, size=5, alpha=1e-4, beta=0.75, k=1.0):
    sq = jnp.square(x)
    half = size // 2
    pad = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (x.ndim - 2)
    padded = jnp.pad(sq, pad)
    acc = jnp.zeros_like(x)
    for i in range(size):
        acc = acc + lax.dynamic_slice_in_dim(padded, i, x.shape[1], axis=1)
    return x / jnp.power(k + alpha * acc, beta)


@register_op("normalize_l2")
def _normalize(x, p=2.0, axis=1, epsilon=1e-12):
    norm = jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis,
                             keepdims=True), 1.0 / p)
    return x / jnp.maximum(norm, epsilon)


# -- losses -----------------------------------------------------------------

def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@register_op("softmax_with_cross_entropy")
def _softmax_ce(logits, label, soft_label=False, axis=-1,
                ignore_index=-100, return_softmax=False):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lbl = label
        squeeze = False
        if lbl.ndim == logits.ndim:
            lbl = jnp.squeeze(lbl, axis=axis)
            squeeze = True
        gathered = jnp.take_along_axis(
            logp, jnp.expand_dims(
                jnp.where(lbl == ignore_index, 0, lbl), axis).astype(
                    jnp.int32), axis=axis)
        loss = -jnp.where(jnp.expand_dims(lbl, axis) == ignore_index,
                          0.0, gathered)
    loss = loss.astype(logits.dtype)
    if return_softmax:
        return loss, jnp.exp(logp).astype(logits.dtype)
    return loss


@register_op("cross_entropy")
def _cross_entropy(logits, label, weight=None, soft_label=False, axis=-1,
                   ignore_index=-100, reduction="mean",
                   use_softmax=True, label_smoothing=0.0):
    axis = axis % logits.ndim
    lf = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(lf, axis=axis) if use_softmax else \
        jnp.log(jnp.maximum(lf, 1e-30))
    n_cls = logits.shape[axis]
    if soft_label:
        sl = label.astype(jnp.float32)
        if label_smoothing > 0:
            sl = sl * (1 - label_smoothing) + label_smoothing / n_cls
        loss = -jnp.sum(sl * logp, axis=axis)
        valid = jnp.ones_like(loss, dtype=bool)
        w = None if weight is None else jnp.sum(
            sl * weight.reshape((1,) * axis + (-1,)), axis=axis)
    else:
        lbl = label
        if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis=axis)
        lbl = lbl.astype(jnp.int32)
        valid = lbl != ignore_index
        safe = jnp.where(valid, lbl, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, axis),
                                     axis=axis)
        nll = -jnp.squeeze(picked, axis=axis)
        if label_smoothing > 0:
            smooth = -jnp.mean(logp, axis=axis)
            nll = (1 - label_smoothing) * nll + label_smoothing * smooth
        loss = jnp.where(valid, nll, 0.0)
        w = None if weight is None else jnp.where(valid, weight[safe], 0.0)
    if w is not None:
        loss = loss * w
    if reduction == "mean":
        if w is not None:
            return (jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)) \
                .astype(logits.dtype)
        denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
        return (jnp.sum(loss) / denom).astype(logits.dtype)
    if reduction == "sum":
        return jnp.sum(loss).astype(logits.dtype)
    return loss.astype(logits.dtype)


@register_op("mse_loss")
def _mse_loss(x, y, reduction="mean"):
    return _reduce_loss(jnp.square(x - y), reduction)


@register_op("l1_loss")
def _l1_loss(x, y, reduction="mean"):
    return _reduce_loss(jnp.abs(x - y), reduction)


@register_op("smooth_l1_loss")
def _smooth_l1(x, y, reduction="mean", delta=1.0):
    d = jnp.abs(x - y)
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce_loss(loss, reduction)


@register_op("huber_loss")
def _huber(x, y, reduction="mean", delta=1.0):
    d = jnp.abs(x - y)
    loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
    return _reduce_loss(loss, reduction)


@register_op("nll_loss")
def _nll_loss(logp, label, weight=None, ignore_index=-100, reduction="mean"):
    lbl = label.astype(jnp.int32)
    valid = lbl != ignore_index
    safe = jnp.where(valid, lbl, 0)
    if logp.ndim > 2:  # N,C,d1..  -> move C last
        moved = jnp.moveaxis(logp, 1, -1)
    else:
        moved = logp
    picked = jnp.take_along_axis(moved, safe[..., None], axis=-1)[..., 0]
    loss = -jnp.where(valid, picked, 0.0)
    w = jnp.where(valid, weight[safe], 0.0) if weight is not None else \
        valid.astype(logp.dtype)
    loss = loss * (weight[safe] if weight is not None else 1.0)
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@register_op("bce_loss")
def _bce(x, label, weight=None, reduction="mean"):
    eps = 1e-12
    loss = -(label * jnp.log(jnp.maximum(x, eps)) +
             (1 - label) * jnp.log(jnp.maximum(1 - x, eps)))
    if weight is not None:
        loss = loss * weight
    return _reduce_loss(loss, reduction)


@register_op("bce_with_logits")
def _bce_logits(x, label, weight=None, pos_weight=None, reduction="mean"):
    softplus_neg_abs = jnp.log1p(jnp.exp(-jnp.abs(x)))
    if pos_weight is not None:
        log_w = (pos_weight - 1) * label + 1
        loss = (1 - label) * x + log_w * (softplus_neg_abs +
                                          jnp.maximum(-x, 0.0))
    else:
        loss = jnp.maximum(x, 0) - x * label + softplus_neg_abs
    if weight is not None:
        loss = loss * weight
    return _reduce_loss(loss, reduction)


@register_op("kl_div")
def _kl_div(x, target, reduction="mean"):
    loss = target * (jnp.log(jnp.maximum(target, 1e-12)) - x)
    loss = jnp.where(target > 0, loss, 0.0)
    if reduction == "batchmean":
        return jnp.sum(loss) / x.shape[0]
    return _reduce_loss(loss, reduction)


@register_op("margin_ranking_loss")
def _margin_ranking(x, y, label, margin=0.0, reduction="mean"):
    return _reduce_loss(jnp.maximum(0.0, -label * (x - y) + margin),
                        reduction)


@register_op("hinge_embedding_loss")
def _hinge_embedding(x, label, margin=1.0, reduction="mean"):
    loss = jnp.where(label == 1, x, jnp.maximum(0.0, margin - x))
    return _reduce_loss(loss, reduction)


@register_op("cosine_similarity")
def _cosine_similarity(x, y, axis=1, eps=1e-8):
    dot = jnp.sum(x * y, axis=axis)
    nx = jnp.sqrt(jnp.sum(x * x, axis=axis))
    ny = jnp.sqrt(jnp.sum(y * y, axis=axis))
    return dot / jnp.maximum(nx * ny, eps)


@register_op("label_smooth")
def _label_smooth(label, prior_dist=None, epsilon=0.1):
    n = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / n


@register_op("sigmoid_focal_loss")
def _sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25,
                        gamma=2.0, reduction="sum"):
    p = jax.nn.sigmoid(logit)
    ce = jnp.maximum(logit, 0) - logit * label + \
        jnp.log1p(jnp.exp(-jnp.abs(logit)))
    p_t = p * label + (1 - p) * (1 - label)
    loss = ce * ((1 - p_t) ** gamma)
    if alpha >= 0:
        a_t = alpha * label + (1 - alpha) * (1 - label)
        loss = a_t * loss
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce_loss(loss, reduction)


# -- misc nn ----------------------------------------------------------------

@register_op("interpolate")
def _interpolate(x, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, data_format="NCHW"):
    nchw = data_format.startswith("NC")
    spatial = x.shape[2:] if nchw else x.shape[1:-1]
    nd = len(spatial)
    if size is None:
        sf = _norm_tuple(scale_factor, nd)
        size = tuple(int(s * f) for s, f in zip(spatial, sf))
    else:
        size = _norm_tuple(size, nd)
    if nchw:
        target = x.shape[:2] + tuple(size)
    else:
        target = (x.shape[0],) + tuple(size) + (x.shape[-1],)
    method = {"nearest": "nearest", "bilinear": "linear",
              "linear": "linear", "trilinear": "linear",
              "bicubic": "cubic", "area": "linear"}[mode]
    if align_corners and method != "nearest":
        # jax.image doesn't support align_corners; emulate with map_coordinates
        return _interp_align_corners(x, size, method, nchw)
    return jax.image.resize(x, target, method=method).astype(x.dtype)


def _interp_align_corners(x, size, method, nchw):
    import jax.scipy.ndimage as ndi
    spatial_axes = list(range(2, x.ndim)) if nchw else \
        list(range(1, x.ndim - 1))
    coords = []
    for ax, out_s in zip(spatial_axes, size):
        in_s = x.shape[ax]
        if out_s == 1:
            c = jnp.zeros((1,))
        else:
            c = jnp.linspace(0, in_s - 1, out_s)
        coords.append(c)
    grids = jnp.meshgrid(*coords, indexing="ij")
    order = 1 if method == "linear" else 0

    def per_image(img):  # img: spatial only
        return ndi.map_coordinates(img, [g for g in grids], order=order)

    batch_axes = tuple(i for i in range(x.ndim) if i not in spatial_axes)
    moved = jnp.moveaxis(x, batch_axes, tuple(range(len(batch_axes))))
    lead = moved.shape[:len(batch_axes)]
    flat = moved.reshape((-1,) + moved.shape[len(batch_axes):])
    out = jax.vmap(per_image)(flat)
    out = out.reshape(lead + out.shape[1:])
    return jnp.moveaxis(out, tuple(range(len(batch_axes))), batch_axes) \
        .astype(x.dtype)


@register_op("pixel_shuffle")
def _pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = int(upscale_factor)
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c // (r * r), r, r, h, w)
        x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
        return x.reshape(n, c // (r * r), h * r, w * r)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, r, r, c // (r * r))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(n, h * r, w * r, c // (r * r))


@register_op("unfold")
def _unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    ks = _norm_tuple(kernel_sizes, 2)
    st = _norm_tuple(strides, 2)
    dl = _norm_tuple(dilations, 2)
    pd = _conv_padding(paddings, 2)
    n, c = x.shape[:2]
    patches = lax.conv_general_dilated_patches(
        x, ks, st, pd, rhs_dilation=dl,
        dimension_numbers=lax.conv_dimension_numbers(
            x.shape, (1, 1) + ks, ("NCHW", "OIHW", "NCHW")))
    return patches.reshape(n, c * ks[0] * ks[1], -1)


@register_op("sequence_mask", nondiff=True)
def _sequence_mask(lengths, maxlen=None, dtype="int64"):
    m = int(maxlen) if maxlen is not None else None
    if m is None:
        raise ValueError("maxlen must be provided under jit")
    r = jnp.arange(m)
    return (r[None, :] < lengths.reshape(-1, 1)).reshape(
        lengths.shape + (m,)).astype(jnp.dtype(dtype))


@register_op("scaled_dot_product_attention")
def _sdpa(q, k, v, mask=None, dropout_key=None, dropout_p=0.0,
          is_causal=False, scale=None):
    """Reference analog: fused_attention_op.cu / fmha_ref.h — here one XLA
    fusion region (Pallas flash-attention override registered separately)."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    # operands keep their storage dtype (bf16 -> native MXU rate);
    # preferred_element_type makes the accumulator f32, which is all the
    # numerics need — upcasting q/k first would force fp32-rate matmuls
    logits = jnp.einsum("...qhd,...khd->...hqk", q, k,
                        preferred_element_type=jnp.float32) * s
    if is_causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("...hqk,...khd->...qhd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)
