"""Op library: pure-jax implementations behind the dispatch layer.

The registry is the analog of the reference's phi KernelFactory; the modules
here are the analog of paddle/phi/kernels/* (reference has 358 op families —
see SURVEY.md §2.1).
"""
from .registry import (get_op, has_op, op_names, register_op,  # noqa: F401
                       register_override)

from . import math_ops  # noqa: F401
from . import creation_ops  # noqa: F401
from . import manipulation_ops  # noqa: F401
from . import linalg_ops  # noqa: F401
from . import nn_ops  # noqa: F401
from . import extra_nn_ops  # noqa: F401
from . import extra_math_ops  # noqa: F401
from . import sequence_ops  # noqa: F401
from . import pallas_kernels  # noqa: F401  (registers TPU overrides)
