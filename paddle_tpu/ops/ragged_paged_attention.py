"""Ragged paged attention — the fused Pallas TPU serving kernel.

The gather-based paged decode step (models/generation.py
``build_paged_decode_fn``) materializes ``pool[li, :, tables]`` per
layer: every request's WHOLE KV window is copied out of the block pool
on every decode step, and attention then runs over the padded
``table_bucket * block_size`` columns for every slot. This kernel is
the TPU-native replacement per "Ragged Paged Attention" (PAPERS.md):
the block pool stays in HBM (``memory_space=ANY``), the kernel walks
each sequence's page table directly — one async DMA per (KV block,
head) into VMEM scratch — and streams online softmax over exactly the
blocks a sequence owns. Nothing is gathered, nothing is padded to the
table bucket, and a single launch serves a RAGGED batch of mixed
prefill-chunk and decode rows (the chunked-prefill unlock).

Layout contract (the serving engine's fused step builds these):

* queries are FLATTENED over the batch: each sequence's ``q_len[s]``
  rows sit contiguously, padded up to a multiple of ``block_q`` (8, the
  fp32 sublane) so one grid step never mixes sequences — decode rows
  cost one padded q block, prefill chunks amortize theirs;
* scalar-prefetch metadata maps grid steps back to sequences:
  ``blk_seq`` names the sequence of each q block (−1 = pad block),
  ``seq_qstart``/``seq_pos0`` recover every row's virtual cache
  position, ``tables`` is the page table, ``kv_len`` bounds the KV walk
  and ``lo`` the valid-window floor (always 0 for paged sequences);
* a row at position ``p`` attends to cache columns ``[lo, p]`` — the
  history PLUS the causal prefix of its own chunk, whose K/V the fused
  step scatters into the pool before the kernel runs.

Mosaic legality (the BENCH_r02 bug class, enforced by the
``pallas-block-tiling`` self-lint): q/o blocks are ``(1, block_q, Dh)``
with ``block_q = 8`` sublane-aligned and ``Dh`` the full array dim; the
KV scratch is ``(block_size, Dh)`` with ``block_size >= 8`` required.

Off-TPU the kernel runs in interpret mode — that is how the tier-1
parity suite (tests/test_ragged_attention.py) executes the kernel body
on CPU.

Tensor-parallel use (ISSUE 15): the kernel is head-count agnostic —
its grid is per-(q block, head), so the sharded serving step
(``build_sharded_fused_step_fn``) simply launches it inside a
``shard_map`` with the LOCAL head count ``H/mp`` against each device's
own pool shard ``[L, 2, blocks, H/mp, bs, Dh]``. No kernel change: the
page tables and scalar-prefetch metadata are replicated (block indices
are shard-invariant), the per-head outputs are partial sums of the
attention projection, and one downstream ``psum`` joins them.
"""
from __future__ import annotations

import functools
import math
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_kernels import _interpret, _x64_off

__all__ = ["ragged_paged_attention", "ragged_layout", "BLOCK_Q",
           "MIN_KV_BLOCK", "min_kv_block_for"]

_NEG_INF = -1e30

# q rows per grid step: the fp32 sublane count — the smallest
# Mosaic-legal second-to-last block dim, so a decode row (1 real query)
# wastes at most 7 pad rows while a prefill chunk fills whole blocks
BLOCK_Q = 8

# the KV scratch block is (block_size, Dh): block_size below the
# sublane count has no legal TPU layout
MIN_KV_BLOCK = 8

# QUANTIZED storage needs a taller minimum tile (the Mosaic
# (sublane, 128) law: int8/fp8 sublane count is 32) — float pools keep
# the historical MIN_KV_BLOCK floor (sub-sublane float blocks already
# ran on the padded-layout path)
_MIN_KV_BLOCK_BY_DTYPE = {"int8": 32, "float8_e4m3fn": 32}


def min_kv_block_for(dtype) -> int:
    """Smallest Mosaic-legal KV ``block_size`` for a pool storage
    dtype (the scratch block's sublane count)."""
    return _MIN_KV_BLOCK_BY_DTYPE.get(jnp.dtype(dtype).name,
                                      MIN_KV_BLOCK)


def _rpa_kernel(blk_seq_ref, qstart_ref, pos0_ref, tables_ref, lo_ref,
                kvlen_ref, *rest, layer, block_q, block_size, scale,
                quantized=False):
    """One (head, q-block) grid step: walk the owning sequence's page
    table, DMA each KV block HBM→VMEM, stream online softmax.

    Quantized pools (int8 blocks) ride a 7th scalar-prefetch operand:
    THIS layer's per-block max-abs scale slice ``[2, NB + 1, H]`` f32 —
    each DMA'd block is dequantized IN-REGISTER (one scalar multiply
    per (block, head) after the VMEM read), so the HBM traffic stays at
    the narrow storage width and nothing quantized ever reaches the
    MXU.

    i32-typed constants: bare python ints in kernel index math get
    materialized as i64 by Mosaic under the framework's global x64 (the
    pallas_kernels idiom; the call sites also trace under _x64_off)."""
    if quantized:
        (scales_ref, q_ref, pool_ref, o_ref, k_scr, v_scr, k_sem,
         v_sem) = rest
    else:
        scales_ref = None
        q_ref, pool_ref, o_ref, k_scr, v_scr, k_sem, v_sem = rest
    h = pl.program_id(0)
    b = pl.program_id(1)
    seq = blk_seq_ref[b]

    @pl.when(seq < 0)
    def _pad_block():
        o_ref[...] = jnp.zeros_like(o_ref)

    @pl.when(seq >= 0)
    def _attend():
        _BS = jnp.int32(block_size)
        _BQ = jnp.int32(block_q)
        q = q_ref[0]                                    # [bq, Dh]
        bq, dh = q.shape
        # virtual cache position of each row: rows of a sequence are
        # consecutive tokens starting at seq_pos0 (pad rows past the
        # real q_len compute masked garbage nobody reads)
        row0 = b * _BQ - qstart_ref[seq]
        qpos = pos0_ref[seq] + row0 + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)                 # [bq, 1]
        lo = lo_ref[seq]
        n_kv = (kvlen_ref[seq] + _BS - 1) // _BS

        def body(j, carry):
            # running softmax stats stay 2D [bq, 1] (sublane-oriented);
            # rank-1 carries would force lane<->sublane relayouts
            m_prev, l_prev, acc = carry
            pid = tables_ref[seq, j]
            # the page-table walk: this sequence's j-th block, this
            # head, copied HBM -> VMEM — the ONLY KV bytes this grid
            # step touches (the gather path would have materialized the
            # whole padded table bucket for every slot)
            ck = pltpu.make_async_copy(
                pool_ref.at[layer, 0, pid, h], k_scr, k_sem)
            cv = pltpu.make_async_copy(
                pool_ref.at[layer, 1, pid, h], v_scr, v_sem)
            ck.start()
            cv.start()
            ck.wait()
            cv.wait()
            k_blk = k_scr[...]                          # [bs, Dh]
            v_blk = v_scr[...]
            if quantized:
                # in-register dequant: the per-(block, head) max-abs
                # scale rides the scalar-prefetch metadata; HBM moved
                # int8, compute sees floats. scales_ref is THIS
                # layer's [2, NB+1, H] slice — prefetching all L
                # layers' scales into SMEM would waste an L-fold
                # bigger scalar-memory footprint per launch
                k_blk = (k_blk.astype(jnp.float32)
                         * scales_ref[0, pid, h]).astype(q.dtype)
                v_blk = (v_blk.astype(jnp.float32)
                         * scales_ref[1, pid, h]).astype(q.dtype)
            # operands in storage dtype, f32 accumulation (MXU contract
            # shared with the flash kernels)
            s = jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale   # [bq, bs]
            cols = j * _BS + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_size), 1)
            # f32-typed fill: a bare python float is weak f64 under the
            # framework's global x64
            s = jnp.where((cols >= lo) & (cols <= qpos), s,
                          jnp.float32(_NEG_INF))
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * alpha + jax.lax.dot_general(
                p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new

        m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((block_q, 1), jnp.float32)
        acc0 = jnp.zeros((block_q, dh), jnp.float32)
        # i32 bounds: a bare python 0 becomes an i64 induction variable
        # under the framework's global x64, and the interpret-mode body
        # trace happens outside the call site's _x64_off scope
        _, l, acc = jax.lax.fori_loop(jnp.int32(0), n_kv, body,
                                      (m0, l0, acc0))
        o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def ragged_paged_attention(q, pool, layer, blk_seq, seq_qstart, seq_pos0,
                           tables, lo, kv_len, *, scales=None, scale=None,
                           block_q: int = BLOCK_Q):
    """Fused paged attention over one layer of the serving block pool.

    * ``q`` — ``[H, Qp, Dh]`` flattened padded query rows (``Qp`` a
      multiple of ``block_q``; per-sequence contiguous, see module doc);
    * ``pool`` — the FULL block pool ``[L, 2, NB + 1, H, bs, Dh]``; it
      stays in HBM (``memory_space=ANY``) and ``layer`` is a static int,
      so no per-layer slice is ever materialized;
    * ``blk_seq [Qp / block_q]``, ``seq_qstart [S]``, ``seq_pos0 [S]``,
      ``tables [S, T]``, ``lo [S]``, ``kv_len [S]`` — int32
      scalar-prefetch metadata (``ragged_layout`` builds the first
      three);
    * ``scales`` — REQUIRED for quantized pools (int8/fp8 storage):
      the per-block max-abs scale array ``[L, 2, NB + 1, H]`` f32,
      riding the scalar-prefetch path into SMEM so each DMA'd block
      dequantizes in-register;
    * returns ``[H, Qp, Dh]`` in ``q``'s dtype.
    """
    h, qp, dh = q.shape
    L, two, nb1, hp, bs, dhp = pool.shape
    quantized = pool.dtype.name in ("int8", "float8_e4m3fn")
    if (hp, dhp) != (h, dh):
        raise ValueError(
            f"pool heads/head_dim {(hp, dhp)} != q {(h, dh)}")
    if qp % block_q:
        raise ValueError(
            f"padded q rows {qp} must be a multiple of block_q {block_q}")
    min_bs = min_kv_block_for(pool.dtype)
    if bs < min_bs:
        raise ValueError(
            f"block_size {bs} < {min_bs}: the {pool.dtype.name} KV "
            f"scratch block has no legal (sublane, 128) TPU tiling "
            f"below the dtype's sublane count")
    if quantized and scales is None:
        raise ValueError(
            f"a {pool.dtype.name} pool is quantized storage: pass the "
            f"per-block scale array (PagedKVPool.scales)")
    if scales is not None and tuple(scales.shape) != (L, 2, nb1, h):
        raise ValueError(
            f"scales shape {tuple(scales.shape)} != per-block layout "
            f"{(L, 2, nb1, h)}")
    if not 0 <= int(layer) < L:
        raise ValueError(f"layer {layer} out of range [0, {L})")
    scale = float(scale) if scale is not None else 1.0 / math.sqrt(dh)
    n_qblk = qp // block_q
    quant = scales is not None
    kernel = functools.partial(
        _rpa_kernel, layer=int(layer), block_q=int(block_q),
        block_size=int(bs), scale=scale, quantized=quant)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=7 if quant else 6,
        grid=(h, n_qblk),
        in_specs=[
            pl.BlockSpec((1, block_q, dh), lambda hh, b, *_: (hh, b, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),   # pool stays in HBM
        ],
        out_specs=pl.BlockSpec((1, block_q, dh),
                               lambda hh, b, *_: (hh, b, 0)),
        scratch_shapes=[
            pltpu.VMEM((bs, dh), pool.dtype),
            pltpu.VMEM((bs, dh), pool.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    prefetch = [jnp.asarray(blk_seq, jnp.int32),
                jnp.asarray(seq_qstart, jnp.int32),
                jnp.asarray(seq_pos0, jnp.int32),
                jnp.asarray(tables, jnp.int32),
                jnp.asarray(lo, jnp.int32),
                jnp.asarray(kv_len, jnp.int32)]
    if quant:
        # only THIS layer's [2, NB+1, H] scale slice goes to SMEM
        prefetch.append(jnp.asarray(scales, jnp.float32)[int(layer)])
    with _x64_off():
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((h, qp, dh), q.dtype),
            interpret=_interpret(),
        )(*prefetch, q, pool)


def ragged_layout(q_lens: Sequence[int], pos0s: Sequence[int], *,
                  block_q: int = BLOCK_Q,
                  q_bucket: int = 0) -> Tuple[np.ndarray, np.ndarray,
                                              np.ndarray, np.ndarray, int]:
    """Host-side row layout of a ragged batch (numpy, scheduler thread).

    ``q_lens[s]`` query rows for sequence ``s`` (0 = absent this
    launch), first token at virtual position ``pos0s[s]``. Each present
    sequence's rows are laid out contiguously and padded to a multiple
    of ``block_q`` so no q block straddles sequences.

    Returns ``(blk_seq, seq_qstart, seq_pos0, last_row, total_rows)``:
    ``blk_seq [q_bucket / block_q]`` int32 (−1 pads), ``seq_qstart`` /
    ``seq_pos0`` ``[S]`` int32, ``last_row [S]`` int32 (flattened row of
    each present sequence's LAST real token; 0 for absent sequences —
    its logits row is garbage the caller ignores), and the unpadded
    ``total_rows``. ``q_bucket`` (a multiple of ``block_q``) fixes the
    padded width; 0 sizes it to the content.
    """
    S = len(q_lens)
    if len(pos0s) != S:
        raise ValueError(f"q_lens/pos0s length mismatch: {S} vs "
                         f"{len(pos0s)}")
    rows_padded = sum(-(-int(n) // block_q) * block_q
                      for n in q_lens if n > 0)
    if q_bucket:
        if q_bucket % block_q:
            raise ValueError(
                f"q_bucket {q_bucket} must be a multiple of block_q "
                f"{block_q}")
        if q_bucket < rows_padded:
            raise ValueError(
                f"q_bucket {q_bucket} cannot hold {rows_padded} padded "
                f"rows")
    else:
        q_bucket = max(rows_padded, block_q)
    blk_seq = np.full(q_bucket // block_q, -1, np.int32)
    seq_qstart = np.zeros(S, np.int32)
    seq_pos0 = np.zeros(S, np.int32)
    last_row = np.zeros(S, np.int32)
    cursor = 0
    total = 0
    for s, n in enumerate(q_lens):
        n = int(n)
        if n <= 0:
            continue
        nblk = -(-n // block_q)
        seq_qstart[s] = cursor
        seq_pos0[s] = int(pos0s[s])
        last_row[s] = cursor + n - 1
        blk_seq[cursor // block_q: cursor // block_q + nblk] = s
        cursor += nblk * block_q
        total += n
    return blk_seq, seq_qstart, seq_pos0, last_row, total


def reference_ragged_attention(q_rows, pool, layer, row_seq, row_pos,
                               tables, lo, scale=None, scales=None):
    """Numpy oracle for the kernel (tests): per-row full-precision
    softmax attention over the row's ``[lo, pos]`` window gathered
    through the page table. ``q_rows [N, H, Dh]``, ``row_seq/row_pos
    [N]``; ``scales`` dequantizes an int8 pool (per-block max-abs,
    the kernel's in-register multiply done up front)."""
    pool = np.asarray(pool, np.float32)
    if scales is not None:
        pool = pool * np.asarray(scales, np.float32)[..., None, None]
    q_rows = np.asarray(q_rows, np.float32)
    n, h, dh = q_rows.shape
    bs = pool.shape[4]
    scale = float(scale) if scale is not None else 1.0 / math.sqrt(dh)
    out = np.zeros_like(q_rows)
    for i in range(n):
        s = int(row_seq[i])
        p = int(row_pos[i])
        cols = np.arange(int(lo[s]), p + 1)
        k = np.stack([pool[layer, 0, tables[s][c // bs], :, c % bs, :]
                      for c in cols])                    # [ctx, H, Dh]
        v = np.stack([pool[layer, 1, tables[s][c // bs], :, c % bs, :]
                      for c in cols])
        for hh in range(h):
            logits = (k[:, hh] @ q_rows[i, hh]) * scale
            w = np.exp(logits - logits.max())
            w /= w.sum()
            out[i, hh] = w @ v[:, hh]
    return out
