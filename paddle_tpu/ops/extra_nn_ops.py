"""Extended nn op families: transposed convs (1d/3d), adaptive 3-D pooling,
fold/unfold adjoints, max-unpooling, grid sampling, temporal shift, CTC loss,
hierarchical sigmoid, margin-based softmax, beam-search ancestry.

Reference analogs: paddle/phi/kernels/{conv_transpose_kernel.h,
pool_kernel.h, fold_kernel.h, unpool_kernel.h, grid_sample_kernel.h,
temporal_shift_kernel.h}, paddle/fluid/operators/{warpctc_op.cc,
hierarchical_sigmoid_op.cc, margin_cross_entropy_op.cu, gather_tree_op.cc}.
All TPU-first: static shapes, lax control flow, gathers/scatters XLA can
fuse — no CUDA-style per-element kernels.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op
from .nn_ops import (_norm_tuple, _conv_padding, _adaptive_pool,
                     _transpose_str_pads)


# ---------------------------------------------------------------------------
# transposed convolutions (reference: conv_transpose_kernel.h)
# ---------------------------------------------------------------------------

def _conv_transpose_nd(x, w, bias, stride, padding, output_padding, dilation,
                       groups, data_format, nd):
    """Fractionally-strided conv: lhs_dilation=stride over the flipped,
    io-swapped kernel — the XLA-native formulation (one conv HLO on the MXU,
    not a scatter)."""
    strides = _norm_tuple(stride, nd)
    pads = _conv_padding(padding, nd)
    dil = _norm_tuple(dilation, nd)
    opad = _norm_tuple(output_padding, nd)
    if isinstance(pads, str):
        spatial = x.shape[2:2 + nd] if data_format.startswith("NC") \
            else x.shape[1:1 + nd]
        if pads.upper() == "SAME":
            dil = (1,) * nd  # reference forces dilation=1 under SAME
        pads = _transpose_str_pads(pads, spatial, w.shape[2:], strides)
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    if groups != 1:
        xs = jnp.split(x, groups, axis=ch_axis)
        ws = jnp.split(w, groups, axis=0)
        outs = [_conv_transpose_nd(xg, wg, None, stride, padding,
                                   output_padding, dilation, 1,
                                   data_format, nd)
                for xg, wg in zip(xs, ws)]
        out = jnp.concatenate(outs, axis=ch_axis)
    else:
        spatial = "DHW"[3 - nd:]
        lhs_spec = ("NC" + spatial) if data_format.startswith("NC") \
            else ("N" + spatial + "C")
        dn = lax.conv_dimension_numbers(
            x.shape, (w.shape[1], w.shape[0]) + w.shape[2:],
            (lhs_spec, "OI" + spatial, lhs_spec))
        pad_trans = [
            (d * (k - 1) - p0, d * (k - 1) - p1 + op)
            for (p0, p1), k, d, op in zip(pads, w.shape[2:], dil, opad)]
        flip = (slice(None), slice(None)) + (slice(None, None, -1),) * nd
        out = lax.conv_general_dilated(
            x, jnp.swapaxes(w, 0, 1)[flip],
            window_strides=(1,) * nd,
            padding=pad_trans,
            lhs_dilation=strides,
            rhs_dilation=dil,
            dimension_numbers=dn)
    if bias is not None:
        if data_format.startswith("NC"):
            out = out + bias.reshape((1, -1) + (1,) * nd)
        else:
            out = out + bias
    return out


@register_op("conv1d_transpose")
def _conv1d_transpose(x, w, bias=None, stride=1, padding=0, output_padding=0,
                      dilation=1, groups=1, data_format="NCL",
                      output_size=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv_transpose_nd(x, w, bias, stride, padding, output_padding,
                              dilation, groups, df, 1)


@register_op("conv3d_transpose")
def _conv3d_transpose(x, w, bias=None, stride=1, padding=0, output_padding=0,
                      dilation=1, groups=1, data_format="NCDHW",
                      output_size=None):
    return _conv_transpose_nd(x, w, bias, stride, padding, output_padding,
                              dilation, groups, data_format, 3)


# ---------------------------------------------------------------------------
# adaptive 3-D pooling
# ---------------------------------------------------------------------------

@register_op("adaptive_avg_pool3d")
def _adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    return _adaptive_pool(x, output_size, 3, data_format, "avg")


@register_op("adaptive_max_pool3d")
def _adaptive_max_pool3d(x, output_size, data_format="NCDHW"):
    return _adaptive_pool(x, output_size, 3, data_format, "max")


# ---------------------------------------------------------------------------
# fold / unpool (reference: fold_kernel.h, unpool_kernel.h)
# ---------------------------------------------------------------------------

@register_op("fold")
def _fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """col2im: adjoint of unfold. x: [N, C*kh*kw, L] -> [N, C, H, W].
    Overlaps accumulate (sum), matching the reference kernel."""
    hs, ws_ = _norm_tuple(output_sizes, 2)
    kh, kw = _norm_tuple(kernel_sizes, 2)
    sh, sw = _norm_tuple(strides, 2)
    dh, dw = _norm_tuple(dilations, 2)
    pd = _conv_padding(paddings, 2)
    (pt, pb), (pl, pr) = pd
    n = x.shape[0]
    c = x.shape[1] // (kh * kw)
    oh = (hs + pt + pb - (dh * (kh - 1) + 1)) // sh + 1
    ow = (ws_ + pl + pr - (dw * (kw - 1) + 1)) // sw + 1
    cols = x.reshape(n, c, kh, kw, oh, ow)
    out = jnp.zeros((n, c, hs + pt + pb, ws_ + pl + pr), x.dtype)
    for i in range(kh):
        for j in range(kw):
            out = out.at[:, :,
                         i * dh:i * dh + (oh - 1) * sh + 1:sh,
                         j * dw:j * dw + (ow - 1) * sw + 1:sw].add(
                cols[:, :, i, j])
    return out[:, :, pt:pt + hs, pl:pl + ws_]


def _max_pool_with_mask(x, kernel_size, stride, padding, nd,
                        ceil_mode=False):
    """Max pool returning (pooled, flat spatial argmax index per window) —
    the reference's return_mask=True contract (pool_kernel.h MaxPoolWithIndex).
    Computed from patches so the index math stays static-shaped for XLA."""
    ks = _norm_tuple(kernel_size, nd)
    st = _norm_tuple(stride if stride is not None else kernel_size, nd)
    pd = _conv_padding(padding, nd)
    spatial = x.shape[2:]
    if ceil_mode:
        # extend right padding so a trailing partial window emits one more
        # output; the extra region holds dtype-min so it never wins argmax
        pd = [(p0, p1 + (-(L + p0 + p1 - k)) % s)
              for (p0, p1), L, k, s in zip(pd, spatial, ks, st)]
    # finite min, not -inf: patch extraction is a one-hot conv and
    # -inf * 0 would poison patches with NaN
    neg = jnp.asarray(jnp.finfo(x.dtype).min
                      if jnp.issubdtype(x.dtype, jnp.floating)
                      else jnp.iinfo(x.dtype).min, x.dtype)
    pad_width = [(0, 0), (0, 0)] + list(pd)
    xp = jnp.pad(x, pad_width, constant_values=neg)
    n, c = x.shape[:2]
    spec = "NCDHW"[:2 + nd] if nd == 3 else ("NCHW" if nd == 2 else "NCW")
    dn = lax.conv_dimension_numbers(xp.shape, (1, 1) + ks,
                                    (spec, "OI" + spec[2:], spec))
    patches = lax.conv_general_dilated_patches(
        xp, ks, st, [(0, 0)] * nd, dimension_numbers=dn)
    out_sp = patches.shape[2:]
    kprod = int(np.prod(ks))
    patches = patches.reshape((n, c, kprod) + out_sp)
    pooled = jnp.max(patches, axis=2)
    win_arg = jnp.argmax(patches, axis=2)  # flat index within the window
    # window offset -> global (unpadded) flat spatial index
    k_unravel = jnp.unravel_index(jnp.arange(kprod), ks)
    g_idx = jnp.zeros((kprod,) + out_sp, jnp.int32)
    for d in range(nd):
        o_coord = jnp.arange(out_sp[d]) * st[d] - pd[d][0]
        shape_o = [1] * (nd + 1)
        shape_o[1 + d] = out_sp[d]
        shape_k = [kprod] + [1] * nd
        coord = (o_coord.reshape(shape_o)
                 + k_unravel[d].astype(jnp.int32).reshape(shape_k))
        stride_flat = int(np.prod(spatial[d + 1:]))
        g_idx = g_idx + coord * stride_flat
    mask = jnp.take_along_axis(
        g_idx[None, None], win_arg[:, :, None], axis=2).squeeze(2)
    return pooled, mask.astype(jnp.int32)


@register_op("max_pool1d_with_mask")
def _max_pool1d_mask(x, kernel_size, stride=None, padding=0,
                     ceil_mode=False):
    return _max_pool_with_mask(x, kernel_size, stride, padding, 1, ceil_mode)


@register_op("max_pool2d_with_mask")
def _max_pool2d_mask(x, kernel_size, stride=None, padding=0,
                     ceil_mode=False):
    return _max_pool_with_mask(x, kernel_size, stride, padding, 2, ceil_mode)


@register_op("max_pool3d_with_mask")
def _max_pool3d_mask(x, kernel_size, stride=None, padding=0,
                     ceil_mode=False):
    return _max_pool_with_mask(x, kernel_size, stride, padding, 3, ceil_mode)


def _max_unpool(x, indices, out_spatial):
    n, c = x.shape[:2]
    hw = int(np.prod(out_spatial))
    l = int(np.prod(x.shape[2:]))
    xf = x.reshape(n * c, l)
    idx = indices.reshape(n * c, l).astype(jnp.int32)
    out = jnp.zeros((n * c, hw), x.dtype)
    out = out.at[jnp.arange(n * c)[:, None], idx].set(xf)
    return out.reshape((n, c) + tuple(out_spatial))


def _unpool_out_size(in_sp, ks, st, pd, output_size, nd):
    if output_size is not None:
        os = tuple(int(v) for v in output_size)
        return os[-nd:]
    return tuple((in_sp[d] - 1) * st[d] - 2 * pd[d][0] + ks[d]
                 for d in range(nd))


@register_op("max_unpool1d")
def _max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                  output_size=None):
    ks = _norm_tuple(kernel_size, 1)
    st = _norm_tuple(stride if stride is not None else kernel_size, 1)
    pd = _conv_padding(padding, 1)
    return _max_unpool(x, indices, _unpool_out_size(
        x.shape[2:], ks, st, pd, output_size, 1))


@register_op("max_unpool2d")
def _max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                  output_size=None):
    ks = _norm_tuple(kernel_size, 2)
    st = _norm_tuple(stride if stride is not None else kernel_size, 2)
    pd = _conv_padding(padding, 2)
    return _max_unpool(x, indices, _unpool_out_size(
        x.shape[2:], ks, st, pd, output_size, 2))


@register_op("max_unpool3d")
def _max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                  output_size=None):
    ks = _norm_tuple(kernel_size, 3)
    st = _norm_tuple(stride if stride is not None else kernel_size, 3)
    pd = _conv_padding(padding, 3)
    return _max_unpool(x, indices, _unpool_out_size(
        x.shape[2:], ks, st, pd, output_size, 3))


# ---------------------------------------------------------------------------
# channel/pixel rearrangement, temporal shift
# ---------------------------------------------------------------------------

@register_op("pixel_unshuffle")
def _pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = int(downscale_factor)
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // r, r, w // r, r)
        x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
        return x.reshape(n, c * r * r, h // r, w // r)
    n, h, w, c = x.shape
    x = x.reshape(n, h // r, r, w // r, r, c)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(n, h // r, w // r, c * r * r)


@register_op("channel_shuffle")
def _channel_shuffle(x, groups, data_format="NCHW"):
    g = int(groups)
    if data_format == "NCHW":
        n, c, h, w = x.shape
        return jnp.transpose(x.reshape(n, g, c // g, h, w),
                             (0, 2, 1, 3, 4)).reshape(n, c, h, w)
    n, h, w, c = x.shape
    return jnp.transpose(x.reshape(n, h, w, g, c // g),
                         (0, 1, 2, 4, 3)).reshape(n, h, w, c)


@register_op("temporal_shift")
def _temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    """TSM shift (reference: temporal_shift_op.cc): first fold of channels
    shifts t-1 -> t, second fold shifts t+1 -> t, rest pass through."""
    if data_format != "NCHW":
        x = jnp.transpose(x, (0, 3, 1, 2))
    nt, c, h, w = x.shape
    t = int(seg_num)
    n = nt // t
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)
    xs = x.reshape(n, t, c, h, w)
    fwd = jnp.concatenate(
        [jnp.zeros_like(xs[:, :1, :c1]), xs[:, :-1, :c1]], axis=1)
    bwd = jnp.concatenate(
        [xs[:, 1:, c1:c2], jnp.zeros_like(xs[:, :1, c1:c2])], axis=1)
    out = jnp.concatenate([fwd, bwd, xs[:, :, c2:]], axis=2)
    out = out.reshape(nt, c, h, w)
    if data_format != "NCHW":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


# ---------------------------------------------------------------------------
# grid sampling (reference: grid_sample_kernel.h, affine_grid_op.cc)
# ---------------------------------------------------------------------------

def _gs_unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1.0) * 0.5 * (size - 1)
    return ((coord + 1.0) * size - 1.0) * 0.5


def _gs_reflect(coord, size, align_corners):
    if align_corners:
        lo, hi = 0.0, float(size - 1)
    else:
        lo, hi = -0.5, size - 0.5
    span = hi - lo
    if span <= 0:
        return jnp.zeros_like(coord)
    c = jnp.abs(coord - lo) % (2 * span)
    return lo + jnp.where(c > span, 2 * span - c, c)


@register_op("grid_sample")
def _grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                 align_corners=True):
    n, c, h, w = x.shape
    gx = _gs_unnormalize(grid[..., 0].astype(jnp.float32), w, align_corners)
    gy = _gs_unnormalize(grid[..., 1].astype(jnp.float32), h, align_corners)
    if padding_mode == "border":
        gx = jnp.clip(gx, 0, w - 1)
        gy = jnp.clip(gy, 0, h - 1)
    elif padding_mode == "reflection":
        gx = jnp.clip(_gs_reflect(gx, w, align_corners), 0, w - 1)
        gy = jnp.clip(_gs_reflect(gy, h, align_corners), 0, h - 1)

    def sample_int(ix, iy):
        """Gather x[n, :, iy, ix] with zero fill for out-of-range."""
        valid = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
        ixc = jnp.clip(ix, 0, w - 1)
        iyc = jnp.clip(iy, 0, h - 1)
        flat = x.reshape(n, c, h * w)
        lin = (iyc * w + ixc).reshape(n, 1, -1)
        vals = jnp.take_along_axis(
            flat, jnp.broadcast_to(lin, (n, c, lin.shape[-1])), axis=2)
        vals = vals.reshape((n, c) + ix.shape[1:])
        return jnp.where(valid[:, None], vals, 0.0)

    if mode == "nearest":
        out = sample_int(jnp.round(gx).astype(jnp.int32),
                         jnp.round(gy).astype(jnp.int32))
        return out.astype(x.dtype)
    x0 = jnp.floor(gx).astype(jnp.int32)
    y0 = jnp.floor(gy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1
    wx = gx - x0
    wy = gy - y0
    v00 = sample_int(x0, y0)
    v01 = sample_int(x1, y0)
    v10 = sample_int(x0, y1)
    v11 = sample_int(x1, y1)
    wxe = wx[:, None]
    wye = wy[:, None]
    out = (v00 * (1 - wxe) * (1 - wye) + v01 * wxe * (1 - wye)
           + v10 * (1 - wxe) * wye + v11 * wxe * wye)
    return out.astype(x.dtype)


@register_op("affine_grid")
def _affine_grid(theta, out_shape, align_corners=True):
    n, _, h, w = [int(v) for v in out_shape]

    def base(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        return (jnp.arange(size) * 2 + 1) / size - 1.0

    ys = base(h)
    xs = base(w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    coords = jnp.stack([gx, gy, ones], axis=-1).reshape(1, h * w, 3)
    out = jnp.einsum("bij,bpj->bpi",
                     theta.astype(jnp.float32),
                     jnp.broadcast_to(coords, (theta.shape[0], h * w, 3)))
    return out.reshape(theta.shape[0], h, w, 2).astype(theta.dtype)


# ---------------------------------------------------------------------------
# gather_tree (reference: gather_tree_op.cc — beam-search ancestry walk)
# ---------------------------------------------------------------------------

@register_op("gather_tree", nondiff=True)
def _gather_tree(ids, parents):
    """ids/parents: [max_time, batch, beam]. Walks parent pointers from the
    last step back, emitting the full sequence per final beam. lax.scan in
    reverse — the TPU-shaped equivalent of the reference's per-beam loop."""
    t, b, k = ids.shape
    beam_iota = jnp.broadcast_to(jnp.arange(k, dtype=ids.dtype), (b, k))

    def step(carry, xs):
        cur_parents = carry
        step_ids, step_parents = xs
        out = jnp.take_along_axis(step_ids, cur_parents, axis=1)
        nxt = jnp.take_along_axis(step_parents, cur_parents, axis=1)
        return nxt, out

    init = beam_iota
    (_, outs) = lax.scan(step, init, (ids[::-1], parents[::-1]))
    return outs[::-1]


# ---------------------------------------------------------------------------
# CTC loss (reference: warpctc_op.cc semantics, TPU-native lax.scan
# forward algorithm in log space — no warp-ctc dependency)
# ---------------------------------------------------------------------------

_NEG_INF = -1e30


@register_op("ctc_loss")
def _ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0):
    """log_probs: [T, N, C] (will be log-softmaxed), labels: [N, L] int,
    returns per-sample negative log likelihood [N]."""
    log_probs = jax.nn.log_softmax(log_probs.astype(jnp.float32), axis=-1)
    t_max, n, _ = log_probs.shape
    l_max = labels.shape[1]
    s = 2 * l_max + 1
    # extended label sequence: blank l1 blank l2 ... lL blank
    ext = jnp.full((n, s), blank, labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    lab_len = label_lengths.astype(jnp.int32).reshape(n)
    in_len = input_lengths.astype(jnp.int32).reshape(n)
    ext_len = 2 * lab_len + 1
    # allow alpha[s] <- alpha[s-2] when ext[s] != blank and ext[s] != ext[s-2]
    ext_prev2 = jnp.concatenate(
        [jnp.full((n, 2), -1, ext.dtype), ext[:, :-2]], axis=1)
    skip_ok = (ext != blank) & (ext != ext_prev2)
    pos = jnp.arange(s)[None, :]

    def emit(lp_t):
        # lp_t: [N, C] -> [N, S] log prob of each extended symbol
        return jnp.take_along_axis(lp_t, ext, axis=1)

    alpha0 = jnp.full((n, s), _NEG_INF)
    alpha0 = alpha0.at[:, 0].set(emit(log_probs[0])[:, 0])
    alpha0 = jnp.where(
        (pos == 1) & (lab_len[:, None] > 0),
        emit(log_probs[0])[:, 1:2], alpha0)

    def step(alpha, xs):
        lp_t, t = xs
        a_shift1 = jnp.concatenate(
            [jnp.full((n, 1), _NEG_INF), alpha[:, :-1]], axis=1)
        a_shift2 = jnp.concatenate(
            [jnp.full((n, 2), _NEG_INF), alpha[:, :-2]], axis=1)
        a_shift2 = jnp.where(skip_ok, a_shift2, _NEG_INF)
        m = jnp.maximum(jnp.maximum(alpha, a_shift1), a_shift2)
        dead = m <= _NEG_INF
        msafe = jnp.where(dead, 0.0, m)
        inner = (jnp.exp(alpha - msafe) + jnp.exp(a_shift1 - msafe)
                 + jnp.exp(a_shift2 - msafe))
        # double-where: log sees a safe value on dead lanes so the untaken
        # branch can't emit NaN cotangents
        summed = msafe + jnp.log(jnp.where(dead, 1.0, inner))
        new = jnp.where(dead, _NEG_INF, summed) + emit(lp_t)
        # past the input length: freeze alpha
        new = jnp.where(t < in_len[:, None], new, alpha)
        return new, None

    ts = jnp.arange(1, t_max)
    alpha, _ = lax.scan(
        step, alpha0, (log_probs[1:], ts))
    # final: alpha[ext_len-1] + alpha[ext_len-2]
    last1 = jnp.take_along_axis(alpha, (ext_len - 1)[:, None], axis=1)[:, 0]
    last2 = jnp.where(
        lab_len > 0,
        jnp.take_along_axis(
            alpha, jnp.maximum(ext_len - 2, 0)[:, None], axis=1)[:, 0],
        _NEG_INF)
    m = jnp.maximum(last1, last2)
    dead = m <= _NEG_INF
    msafe = jnp.where(dead, 0.0, m)
    inner = jnp.exp(last1 - msafe) + jnp.exp(last2 - msafe)
    ll = msafe + jnp.log(jnp.where(dead, 1.0, inner))
    return -ll


# ---------------------------------------------------------------------------
# hierarchical sigmoid (reference: hierarchical_sigmoid_op.cc SimpleCode)
# ---------------------------------------------------------------------------

@register_op("hsigmoid_loss")
def _hsigmoid_loss(x, label, weight, bias=None, path_table=None,
                   path_code=None, num_classes=2):
    """Default tree = the reference's SimpleCode complete binary tree:
    code = label + num_classes; node index at depth j = (code >> (len-j)) - 1,
    branch bit = (code >> (len-1-j)) & 1. Custom trees via path_table (node
    ids, -1 padded) + path_code (branch bits)."""
    xf = x.astype(jnp.float32)
    n = x.shape[0]
    if path_table is None:
        depth_max = int(np.ceil(np.log2(max(int(num_classes), 2))))
        code = label.astype(jnp.int32).reshape(n) + int(num_classes)
        # length = floor(log2(code)); vectorized over the batch
        lengths = (jnp.floor(jnp.log2(code.astype(jnp.float32)))
                   .astype(jnp.int32))
        j = jnp.arange(depth_max)[None, :]
        active = j < lengths[:, None]
        idx = jnp.where(active,
                        (code[:, None] >> (lengths[:, None] - j)) - 1, 0)
        bits = jnp.where(
            active,
            (code[:, None] >> (lengths[:, None] - 1 - j)) & 1, 0)
    else:
        idx = path_table.astype(jnp.int32)
        active = idx >= 0
        idx = jnp.where(active, idx, 0)
        bits = jnp.where(active, path_code.astype(jnp.int32), 0)
    w_nodes = weight.astype(jnp.float32)[idx]        # [N, D, F]
    logits = jnp.einsum("nf,ndf->nd", xf, w_nodes)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32).reshape(-1)[idx]
    # binary logistic loss at every active node:
    #   bit=1 -> -log sigmoid(logit);  bit=0 -> -log sigmoid(-logit)
    per_node = jax.nn.softplus(logits) - bits * logits
    loss = jnp.sum(jnp.where(active, per_node, 0.0), axis=1, keepdims=True)
    return loss.astype(x.dtype)


# ---------------------------------------------------------------------------
# margin cross entropy (reference: margin_cross_entropy_op.cu — ArcFace
# combined margin over cosine logits) + class_center_sample
# ---------------------------------------------------------------------------

@register_op("margin_cross_entropy")
def _margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                          margin3=0.0, scale=64.0, return_softmax=False):
    lf = logits.astype(jnp.float32)
    n, c = lf.shape
    lab = label.astype(jnp.int32).reshape(n)
    onehot = jax.nn.one_hot(lab, c, dtype=jnp.float32)
    target = jnp.sum(lf * onehot, axis=1)
    theta = jnp.arccos(jnp.clip(target, -1.0, 1.0))
    target_m = jnp.cos(margin1 * theta + margin2) - margin3
    mod = lf * (1 - onehot) + target_m[:, None] * onehot
    mod = mod * scale
    logp = jax.nn.log_softmax(mod, axis=1)
    loss = (-jnp.sum(logp * onehot, axis=1, keepdims=True)).astype(
        logits.dtype)
    if return_softmax:
        return loss, jnp.exp(logp).astype(logits.dtype)
    return loss


@register_op("class_center_sample", nondiff=True, jit=False)
def _class_center_sample(label, num_classes, num_samples, seed=None):
    """Uniform-negative class-center sampling (PLSC / partial-fc style,
    reference: class_center_sample_op.cu). Eager-only: the sampled id set is
    data-dependent, so it runs on host numpy (the result feeds a gather whose
    shape IS static: num_samples)."""
    lab = np.asarray(label).reshape(-1)
    rng = np.random.RandomState(seed)
    pos = np.unique(lab)
    n_total = int(num_classes)
    n_samp = int(num_samples)
    if len(pos) >= n_samp:
        # positives are never dropped (reference keeps all positives and
        # num_samples acts as a floor topped up with negatives)
        sampled = pos
    else:
        neg_pool = np.setdiff1d(np.arange(n_total), pos, assume_unique=True)
        extra = rng.choice(neg_pool, size=n_samp - len(pos), replace=False)
        sampled = np.concatenate([pos, extra])
    sampled = np.sort(sampled)
    remap = np.full(n_total, -1, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (jnp.asarray(remap[lab]), jnp.asarray(sampled))


# ---------------------------------------------------------------------------
# sparse attention (reference: nn/functional/sparse_attention.py — CSR
# block pattern). Semantics-exact: CSR -> dense mask -> masked softmax.
# On TPU the dense masked form IS the fast path for moderate sparsity
# (MXU-friendly); a Pallas block-sparse kernel can override later.
# ---------------------------------------------------------------------------

@register_op("sparse_attention")
def _sparse_attention(q, k, v, offset, columns, key_padding_mask=None,
                      attn_mask=None):
    b, h, l, d = q.shape
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    nnz = columns.shape[-1]
    # build dense mask from CSR: valid (row, col) pairs
    pos = jnp.arange(nnz)[None, None, :]
    # map each nnz slot to its row: row r owns slots [offset[r], offset[r+1])
    row_id = jnp.sum(pos[..., None, :] >= offset[..., 1:, None],
                     axis=-2)                              # [B,H,nnz]
    valid = pos < offset[..., -1:, None][..., 0, :]
    mask = jnp.zeros((b, h, l, l), bool)
    bb = jnp.arange(b)[:, None, None]
    hh = jnp.arange(h)[None, :, None]
    mask = mask.at[bb, hh, row_id, columns].max(valid)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) / jnp.sqrt(float(d))
    if key_padding_mask is not None:
        # [B, L] additive (0 keep / -INF drop), reference sparse_attention.py
        logits = logits + key_padding_mask.astype(
            jnp.float32)[:, None, None, :]
    if attn_mask is not None:
        logits = logits + attn_mask.astype(jnp.float32)[None, None]
    logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    probs = jnp.where(mask, probs, 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)
