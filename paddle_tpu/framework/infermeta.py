"""InferMeta: shape/dtype inference and pre-dispatch validation.

Analog of the reference's phi/infermeta/ (unary.cc/binary.cc/multiary.cc):
per-op shape checks shared by every execution mode, raising before any
kernel runs. Two tiers here:

1. ``infer_meta(op, *specs, **attrs)`` — generic compute-free shape/dtype
   inference for ANY registered op via ``jax.eval_shape`` (the whole 11k-LoC
   reference infermeta table collapses onto the tracer).
2. Curated validators for the most-called ops, raising reference-style
   ShapeError messages with both operands' shapes in the text — XLA's own
   errors fire deep inside jit where the user can't see their call site.

Validation runs on every eager ``call_op`` (cheap rank/size Python checks,
same cost class as the reference running InferMeta per kernel launch);
``FLAGS_check_shapes=False`` disables it.
"""
from __future__ import annotations

from typing import Callable, Dict

import numpy as np

__all__ = ["infer_meta", "register_infermeta", "maybe_check", "ShapeError"]


class ShapeError(ValueError):
    """Reference analog: phi::errors::InvalidArgument from InferMeta."""


_VALIDATORS: Dict[str, Callable] = {}


def register_infermeta(name):
    def deco(fn):
        _VALIDATORS[name] = fn
        return fn

    return deco


def _shape(x):
    s = getattr(x, "shape", None)
    return tuple(s) if s is not None else ()


def maybe_check(name, args, attrs):
    v = _VALIDATORS.get(name)
    if v is not None:
        v(*args, **attrs)


def infer_meta(op_name, *specs, **attrs):
    """Shape/dtype inference without compute. Accepts Tensors, arrays, or
    ``jax.ShapeDtypeStruct``; returns ShapeDtypeStruct pytree."""
    import jax

    from ..ops.registry import get_op
    from .tensor import Tensor

    impl = get_op(op_name).fn

    def to_spec(x):
        if isinstance(x, Tensor):
            return jax.ShapeDtypeStruct(tuple(x._data.shape), x._data.dtype)
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(tuple(x.shape), np.dtype(x.dtype))
        return x

    mapped = jax.tree_util.tree_map(
        to_spec, list(specs),
        is_leaf=lambda x: isinstance(x, (Tensor, jax.ShapeDtypeStruct)))
    return jax.eval_shape(lambda *a: impl(*a, **attrs), *mapped)


# ---------------------------------------------------------------------------
# curated validators (reference: phi/infermeta/binary.cc MatmulInferMeta,
# multiary.cc ConcatInferMeta, ConvInferMeta, EmbeddingInferMeta, ...)
# ---------------------------------------------------------------------------

@register_infermeta("matmul")
def _matmul_meta(x, y, transpose_x=False, transpose_y=False, **_):
    xs, ys = _shape(x), _shape(y)
    if not xs or not ys:
        return
    if len(xs) == 1 and len(ys) == 1:
        if xs[0] != ys[0]:
            raise ShapeError(
                f"matmul: 1-D operands must agree, got {xs} vs {ys}")
        return
    kx = xs[-2] if (transpose_x and len(xs) > 1) else xs[-1]
    ky = ys[-1] if (transpose_y and len(ys) > 1) else \
        (ys[-2] if len(ys) > 1 else ys[0])
    if kx != ky:
        raise ShapeError(
            f"matmul: contracted dims must agree, got X{list(xs)} "
            f"(transpose_x={transpose_x}) vs Y{list(ys)} "
            f"(transpose_y={transpose_y}): {kx} != {ky}")


@register_infermeta("concat")
def _concat_meta(xs, axis=0, **_):
    if not isinstance(xs, (list, tuple)) or len(xs) < 1:
        raise ShapeError("concat: expects a non-empty list of tensors")
    shapes = [_shape(x) for x in xs]
    r = len(shapes[0])
    if r and not -r <= axis < r:
        raise ShapeError(f"concat: axis {axis} out of range for rank {r}")
    ax = axis % r if r else 0
    for s in shapes[1:]:
        if len(s) != r:
            raise ShapeError(
                f"concat: ranks differ, got {[list(s) for s in shapes]}")
        for d in range(r):
            if d != ax and s[d] != shapes[0][d]:
                raise ShapeError(
                    f"concat: non-axis dims must agree along axis {axis}, "
                    f"got {[list(s) for s in shapes]}")


@register_infermeta("conv2d")
def _conv2d_meta(x, w, bias=None, groups=1, data_format="NCHW", **_):
    xs, ws = _shape(x), _shape(w)
    if len(xs) != 4 or len(ws) != 4:
        raise ShapeError(
            f"conv2d: input/filter must be 4-D, got x{list(xs)} w{list(ws)}")
    cin = xs[1] if data_format.startswith("NC") else xs[-1]
    if cin != ws[1] * groups:
        raise ShapeError(
            f"conv2d: input channels {cin} != filter in-channels "
            f"{ws[1]} * groups {groups} (x{list(xs)}, w{list(ws)})")
    if ws[0] % groups != 0:
        raise ShapeError(
            f"conv2d: out channels {ws[0]} not divisible by groups {groups}")


@register_infermeta("embedding")
def _embedding_meta(ids, weight, **_):
    ws = _shape(weight)
    if len(ws) != 2:
        raise ShapeError(
            f"embedding: weight must be 2-D [vocab, dim], got {list(ws)}")


@register_infermeta("linear")
def _linear_meta(x, w, bias=None, **_):
    xs, ws = _shape(x), _shape(w)
    if len(ws) != 2:
        raise ShapeError(f"linear: weight must be 2-D, got {list(ws)}")
    if xs and xs[-1] != ws[0]:
        raise ShapeError(
            f"linear: input feature dim {xs[-1]} != weight rows {ws[0]} "
            f"(x{list(xs)}, w{list(ws)})")
    if bias is not None:
        bs = _shape(bias)
        if bs and bs[-1] != ws[1]:
            raise ShapeError(
                f"linear: bias dim {bs[-1]} != out features {ws[1]}")


@register_infermeta("cross_entropy")
def _ce_meta(logits, label, weight=None, soft_label=False, axis=-1, **_):
    ls, ys = _shape(logits), _shape(label)
    if soft_label:
        if ls != ys:
            raise ShapeError(
                f"cross_entropy(soft_label): logits {list(ls)} and label "
                f"{list(ys)} must match")
        return
    if ls and ys and len(ys) not in (len(ls) - 1, len(ls)):
        raise ShapeError(
            f"cross_entropy: label rank {len(ys)} incompatible with logits "
            f"rank {len(ls)} (logits {list(ls)}, label {list(ys)})")


@register_infermeta("batch_norm")
def _bn_meta(x, mean, var, weight=None, bias=None, data_format="NCHW", **_):
    xs = _shape(x)
    if len(xs) < 2:
        raise ShapeError(f"batch_norm: input must be ≥2-D, got {list(xs)}")
    c = xs[1] if data_format.startswith("NC") else xs[-1]
    for nm, t in (("mean", mean), ("variance", var), ("weight", weight),
                  ("bias", bias)):
        if t is None:
            continue
        ts = _shape(t)
        if ts and ts[0] != c:
            raise ShapeError(
                f"batch_norm: {nm} has {ts[0]} channels, input has {c} "
                f"(x{list(xs)})")


@register_infermeta("reshape")
def _reshape_meta(x, shape=None, **_):
    if shape is None:
        return
    xs = _shape(x)
    total = int(np.prod(xs)) if xs else 1
    tgt = list(shape)
    n_minus = sum(1 for d in tgt if d == -1)
    if n_minus > 1:
        raise ShapeError(f"reshape: at most one -1 allowed, got {tgt}")
    if not all(isinstance(d, (int, np.integer)) for d in tgt):
        return  # symbolic dims: leave to the tracer
    known = 1
    for i, d in enumerate(tgt):
        if d == 0:  # reference: 0 copies the input dim at that position
            known *= xs[i] if i < len(xs) else 1
        elif d > 0:
            known *= d
    if n_minus == 0 and known != total:
        raise ShapeError(
            f"reshape: cannot reshape {list(xs)} ({total} elements) into "
            f"{tgt} ({known} elements)")
    if n_minus == 1 and (known == 0 or total % known != 0):
        raise ShapeError(
            f"reshape: cannot infer -1 for {list(xs)} -> {tgt}: {total} "
            f"not divisible by {known}")


@register_infermeta("split")
def _split_meta(x, num_or_sections=None, axis=0, **_):
    xs = _shape(x)
    if not xs or num_or_sections is None:
        return
    if not -len(xs) <= axis < len(xs):
        raise ShapeError(
            f"split: axis {axis} out of range for rank {len(xs)}")
    ax = axis % len(xs)
    size = xs[ax]
    if isinstance(num_or_sections, int):
        if size % num_or_sections != 0:
            raise ShapeError(
                f"split: dim {ax} of size {size} not divisible into "
                f"{num_or_sections} parts (x{list(xs)})")
    else:
        secs = [s for s in num_or_sections]
        if -1 not in secs and sum(secs) != size:
            raise ShapeError(
                f"split: sections {secs} must sum to dim {ax} size {size}")


@register_infermeta("one_hot")
def _one_hot_meta(x, num_classes=None, **_):
    if num_classes is not None and int(num_classes) < 1:
        raise ShapeError(f"one_hot: num_classes must be ≥1, got "
                         f"{num_classes}")


@register_infermeta("transpose")
def _transpose_meta(x, perm=None, **_):
    if perm is None:
        return
    xs = _shape(x)
    if len(perm) != len(xs):
        raise ShapeError(
            f"transpose: perm {list(perm)} length must equal input rank "
            f"{len(xs)} (x{list(xs)})")
    if sorted(perm) != list(range(len(xs))):
        raise ShapeError(f"transpose: perm {list(perm)} is not a "
                         f"permutation of 0..{len(xs) - 1}")


@register_infermeta("expand")
def _expand_meta(x, shape=None, **_):
    if shape is None:
        return
    xs = _shape(x)
    if len(shape) < len(xs):
        raise ShapeError(
            f"expand: target rank {len(shape)} < input rank {len(xs)}")
    for xd, td in zip(xs[::-1], list(shape)[::-1]):
        if td != -1 and xd not in (1, td):
            raise ShapeError(
                f"expand: cannot expand {list(xs)} to {list(shape)}: dim "
                f"{xd} vs {td}")


@register_infermeta("gather")
def _gather_meta(x, index, axis=0, **_):
    xs = _shape(x)
    if xs and not -len(xs) <= axis < len(xs):
        raise ShapeError(
            f"gather: axis {axis} out of range for rank {len(xs)}")


@register_infermeta("layer_norm")
def _ln_meta(x, weight=None, bias=None, begin_norm_axis=None, **_):
    xs = _shape(x)
    if begin_norm_axis is None or not xs:
        return
    norm_shape = xs[begin_norm_axis:]
    n = int(np.prod(norm_shape)) if norm_shape else 1
    for nm, t in (("weight", weight), ("bias", bias)):
        if t is None:
            continue
        ts = _shape(t)
        if ts and int(np.prod(ts)) != n:
            raise ShapeError(
                f"layer_norm: {nm} shape {list(ts)} must cover normalized "
                f"shape {list(norm_shape)} of x{list(xs)}")
