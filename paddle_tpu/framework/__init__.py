"""Core framework: dtypes, places, flags, RNG, Tensor, dispatch, autograd."""
import jax as _jax

# Full dtype coverage (float64/int64 like the reference) — XLA still computes
# in 32-bit unless explicitly asked for 64-bit values.
_jax.config.update("jax_enable_x64", True)

# jax < 0.5 ships shard_map only under jax.experimental (and with the
# pre-rename kwargs: auto/check_rep instead of axis_names/check_vma);
# every sharded path here (collectives, SPMD engine, pipeline, ring
# attention) uses the public jax.shard_map surface, so adapt it on older
# images: axis_names lists the axes that go MANUAL, which is the
# complement of the old `auto` set.
if not hasattr(_jax, "shard_map"):
    try:
        from jax.experimental.shard_map import shard_map as _sm_old

        def _shard_map_compat(f, mesh, in_specs, out_specs,
                              axis_names=None, check_vma=None, **kw):
            if axis_names is not None:
                kw["auto"] = frozenset(mesh.axis_names) - \
                    frozenset(axis_names)
            if check_vma is not None:
                kw["check_rep"] = check_vma
            return _sm_old(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **kw)

        _jax.shard_map = _shard_map_compat
    except Exception as _e:  # pragma: no cover - depends on jax build
        import warnings
        warnings.warn(
            f"jax.shard_map unavailable and the compat import failed "
            f"({_e!r}); sharded paths will raise AttributeError")

from . import dtypes  # noqa: E402,F401
from .dtypes import (bfloat16, bool_, complex64, complex128,  # noqa: E402,F401
                     convert_dtype, float16, float32, float64,
                     get_default_dtype, int8, int16, int32, int64,
                     set_default_dtype, uint8)
from .enforce import (EnforceNotMet, InvalidArgumentError,  # noqa: E402,F401
                      enforce)
from .flags import define_flag, get_flags, set_flags  # noqa: E402,F401
from .place import (CPUPlace, CUDAPinnedPlace, CUDAPlace, NPUPlace,  # noqa: E402,F401
                    Place, TPUPlace,
                    current_place, get_device, is_compiled_with_tpu,
                    set_device)
from .random import (default_generator, rng_guard, seed)  # noqa: E402,F401
from .tensor import (GradNode, Parameter, Tensor,  # noqa: E402,F401
                     is_grad_enabled, no_grad, no_grad_guard, run_backward)
from .dispatch import call_op  # noqa: E402,F401

# env-seeded persistent XLA compilation cache: FLAGS_compile_cache=1
# arms it for the whole process at import, mirroring FLAGS_enable_profiler
from . import compile_cache  # noqa: E402,F401
compile_cache.maybe_enable()
