"""Core framework: dtypes, places, flags, RNG, Tensor, dispatch, autograd."""
import jax as _jax

# Full dtype coverage (float64/int64 like the reference) — XLA still computes
# in 32-bit unless explicitly asked for 64-bit values.
_jax.config.update("jax_enable_x64", True)

from . import dtypes  # noqa: E402,F401
from .dtypes import (bfloat16, bool_, complex64, complex128,  # noqa: E402,F401
                     convert_dtype, float16, float32, float64,
                     get_default_dtype, int8, int16, int32, int64,
                     set_default_dtype, uint8)
from .enforce import (EnforceNotMet, InvalidArgumentError,  # noqa: E402,F401
                      enforce)
from .flags import define_flag, get_flags, set_flags  # noqa: E402,F401
from .place import (CPUPlace, CUDAPinnedPlace, CUDAPlace, NPUPlace,  # noqa: E402,F401
                    Place, TPUPlace,
                    current_place, get_device, is_compiled_with_tpu,
                    set_device)
from .random import (default_generator, rng_guard, seed)  # noqa: E402,F401
from .tensor import (GradNode, Parameter, Tensor,  # noqa: E402,F401
                     is_grad_enabled, no_grad, no_grad_guard, run_backward)
from .dispatch import call_op  # noqa: E402,F401
