"""Eager op dispatch.

The analog of the reference's generated dygraph forward functions
(/root/reference/paddle/fluid/eager/auto_code_generator/final_state_generator/
eager_gen.py:853) + phi kernel selection (phi/api/lib/kernel_dispatch.h). One
generic path replaces per-op codegen:

  user API  ->  call_op(name, *args, **attrs)
                  unwrap Tensors -> jax arrays
                  select impl (registry; Pallas overrides)
                  jax.jit-cached execution          (kernel launch)
                  jax.vjp + GradNode when grad needed (node creation)
                  wrap outputs in Tensors

Caching: one compiled executable per (op, attrs, input avals) — jax.jit's
cache keyed by our (op, attrs, arg-structure) closure. This plays the role of
the reference's OpCache/kernel-factory lookups in the eager hot loop.
"""
from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.registry import get_op
from .infermeta import maybe_check as _infermeta_check
from . import dtypes as _dtypes
from . import program_registry as _registry
from . import static_capture as _capture
from .flags import flag_value
from .monitor import stat_add, stat_observe
from . import trace_probe as _probe
from .tensor import GradNode, Tensor, is_grad_enabled
from ..profiler import span as _prof

Array = Any


class _Slot:
    __slots__ = ("idx",)

    def __init__(self, idx):
        self.idx = idx


def _unwrap_args(args) -> Tuple[list, list]:
    """Replace Tensor leaves (incl. one level of list/tuple nesting) with
    slots; return (template, tensors)."""
    tensors: List[Tensor] = []

    def _as_input(a):
        # Raw jax/numpy arrays must be traced inputs too (NOT closure
        # constants): the jit cache is keyed by shape/dtype only, so baking
        # values into the closure would serve stale data.
        if isinstance(a, Tensor):
            return a
        if isinstance(a, (jax.Array, np.ndarray)) or hasattr(a, "aval"):
            return Tensor(jnp.asarray(a), stop_gradient=True)
        return None

    template = []
    for a in args:
        t = _as_input(a)
        if t is not None:
            tensors.append(t)
            template.append(_Slot(len(tensors) - 1))
        elif isinstance(a, (list, tuple)) and any(
                _as_input(x) is not None for x in a):
            sub = []
            for x in a:
                t = _as_input(x)
                if t is not None:
                    tensors.append(t)
                    sub.append(_Slot(len(tensors) - 1))
                else:
                    sub.append(x)
            template.append(type(a)(sub) if isinstance(a, tuple) else sub)
        else:
            template.append(a)
    return template, tensors


def _rebuild(template, arrays):
    out = []
    for a in template:
        if isinstance(a, _Slot):
            out.append(arrays[a.idx])
        elif isinstance(a, list):
            out.append([arrays[x.idx] if isinstance(x, _Slot) else x
                        for x in a])
        elif isinstance(a, tuple):
            out.append(tuple(arrays[x.idx] if isinstance(x, _Slot) else x
                             for x in a))
        else:
            out.append(a)
    return out


def _template_key(template):
    parts = []
    for a in template:
        if isinstance(a, _Slot):
            parts.append(("T", a.idx))
        elif isinstance(a, (list, tuple)):
            parts.append((type(a).__name__,
                          tuple(("T", x.idx) if isinstance(x, _Slot)
                                else ("C", _const_key(x)) for x in a)))
        else:
            parts.append(("C", _const_key(a)))
    return tuple(parts)


# digest memo for array-valued constants: id -> (weakref, key). Hashing a
# big constant costs O(bytes); memoising by identity makes the repeated
# dispatch of the same constant O(1) (r3 verdict weak #7). The weakref
# guards against id reuse after GC.
_arr_key_memo: Dict[int, tuple] = {}


def _const_key(v):
    if isinstance(v, (np.ndarray, jnp.ndarray)):
        # Arrays should normally be routed through the traced-input path
        # (see call_op); if one still lands here as a constant, key it by
        # VALUE, not just shape/dtype, so distinct constants never alias.
        # The identity memo applies ONLY to jax.Arrays — they are
        # immutable, so identity implies value. A np.ndarray can be
        # mutated in place (same id, same object), which would serve a
        # stale digest; those hash every call.
        import hashlib
        memoizable = isinstance(v, jnp.ndarray) and \
            not isinstance(v, np.ndarray)
        if memoizable:
            memo = _arr_key_memo.get(id(v))
            if memo is not None and memo[0]() is v:
                return memo[1]
        key = ("arr", v.shape, str(v.dtype),
               hashlib.sha1(np.ascontiguousarray(v)).digest())
        if memoizable:
            import weakref
            try:
                if len(_arr_key_memo) > 512:
                    _arr_key_memo.clear()  # bound the memo
                _arr_key_memo[id(v)] = (weakref.ref(v), key)
            except TypeError:
                pass  # not weakref-able: skip the memo
        return key
    if isinstance(v, (tuple, list)):
        # recurse: (1, 2) == (1.0, 2.0) alias elementwise, same bug one
        # level down; lists must NOT fall through to repr() — numpy's
        # repr truncates big arrays, which would alias distinct values
        return (type(v).__name__, tuple(_const_key(x) for x in v))
    try:
        hash(v)
    except TypeError:
        return repr(v)
    # include the python type: 1 == 1.0 == True hash-alias as dict keys,
    # which would serve a float-scalar compiled op for an int scalar (the
    # add(int32, 1) -> float64 bug)
    return (type(v).__name__, v)


_fn_cache: Dict[tuple, Any] = {}


def _get_callable(name: str, impl, template, attrs_key, attrs,
                  arr_attr_names=(), jit_ok=True):
    key = (name, id(impl), _template_key(template), attrs_key,
           tuple(arr_attr_names))
    fn = _fn_cache.get(key)
    if fn is None:
        # a miss means a NEW (op, attrs, structure) class: a jit wrapper
        # is built here and XLA compiles on its first call. The counter
        # pair makes cache-thrash regressions (e.g. an attrs key aliasing
        # bug exhausting XLA, 3edc4ce) a visible metric, not a post-mortem.
        stat_add("op_cache_miss")
        stat_add(f"op_cache_miss/{name}")
        fn = _build_callable(impl, template, attrs, arr_attr_names, jit_ok,
                             probe_name=name, probe_static=attrs_key)
        fn = _first_call_probe(
            name, key, fn,
            jitted=jit_ok and flag_value("FLAGS_eager_jit_ops"))
        _fn_cache[key] = fn
    else:
        stat_add("op_cache_hit")
    return fn


def _first_call_probe(name, key, built, jitted=True):
    """Attribute the REAL compile cost: the jax.jit wrapper is cheap,
    XLA compiles at the first invocation — so on a miss, time that
    first call (trace+compile+first run) into the program registry
    (``compile/ms/op/<name>`` histogram + ``compile/count``; the
    registry's dispatch-layer approximation — the op cache must stay
    jax-owned, so no cost analysis here) and, while a profiler session
    is armed, additionally span it as jit_compile/<op> ("cache"
    category). ``jitted=False`` (a jit=False op, or FLAGS_eager_jit_ops
    off) keeps the span but skips the registry note — an eager first
    call compiles nothing, and the always-on compile counters must
    never count one. Self-replaces the cache entry with the raw
    callable, leaving zero steady-state overhead."""
    def traced(*arrays):
        if _fn_cache.get(key) is not built:
            if any(isinstance(a, jax.core.Tracer) for a in arrays):
                # abstract first call (make_jaxpr / an outer trace, e.g.
                # the fit-before-compile planner): the wrapper inlines
                # into the outer jaxpr without XLA compiling anything —
                # keep compile/count untouched and the probe armed for
                # the first CONCRETE call, where the compile cost lands.
                # It is still a jit-cache miss, so an armed profiler
                # session sees it as a "cache" span under its own name
                if _prof._active:
                    with _prof.record(f"jit_trace/{name}", "cache"):
                        return built(*arrays)
                return built(*arrays)
            _fn_cache[key] = built
            t0 = time.perf_counter()
            if _prof._active:
                with _prof.record(f"jit_compile/{name}", "cache"):
                    out = built(*arrays)
            else:
                out = built(*arrays)
            if jitted:
                _registry.note_compile(f"op/{name}",
                                       (time.perf_counter() - t0) * 1e3)
            return out
        return built(*arrays)  # replayed wrapper ref (static capture)

    return traced


def _build_callable(impl, template, attrs, arr_attr_names, jit_ok,
                    probe_name=None, probe_static=None):
    n_attr = len(arr_attr_names)

    def raw(*arrays):
        pos = arrays[:len(arrays) - n_attr] if n_attr else arrays
        kw = dict(attrs)
        if n_attr:
            kw.update(zip(arr_attr_names,
                          arrays[len(arrays) - n_attr:]))
        return impl(*_rebuild(template, pos), **kw)

    if jit_ok and flag_value("FLAGS_eager_jit_ops"):
        if probe_name is not None:
            # under jit, ``raw`` runs only while TRACING a new signature
            # — so recording here counts (and classifies) every retrace
            # of this op at trace time, at zero steady-state cost
            # (framework/trace_probe.py; the dispatch/retrace_cause
            # counters feed the recompile-churn analysis pass)
            site = _probe.site(f"op/{probe_name}")
            static = {"attrs": probe_static}
            inner = raw

            def raw(*arrays, _inner=inner, _site=site, _static=static):
                _site.record(_probe.sig_of(arrays), _static)
                return _inner(*arrays)

        return jax.jit(raw)
    return raw


def _get_bwd_callable(name: str, impl, template, attrs_key, fwd_fn,
                      arr_attr_names=(), jit_ok=True):
    """Jitted pullback for (op, attrs, structure): ``bwd(ct, *arrays)``
    recomputes the forward linearization inside jit and returns input
    cotangents. Cached like the forward callable, so after the first
    backward per shape class the eager tape pays ONE compiled call per
    node instead of an eager jax.vjp re-trace (the pre-r5 ~40x per-op
    overhead). ``fwd_fn`` is the already-cached forward callable —
    jax.vjp through it reuses its trace under this jit."""
    key = ("bwd", name, id(impl), _template_key(template), attrs_key,
           tuple(arr_attr_names))
    fn = _fn_cache.get(key)
    if fn is None:
        stat_add("op_cache_miss")
        stat_add(f"op_cache_miss/{name}.bwd")

        def bwd_raw(ct, *arrays):
            _, vjp = jax.vjp(fwd_fn, *arrays)
            return vjp(ct)

        if jit_ok and flag_value("FLAGS_eager_jit_ops"):
            bsite = _probe.site(f"op/{name}.bwd")
            bstatic = {"attrs": attrs_key}
            inner_bwd = bwd_raw

            def bwd_raw(ct, *arrays, _inner=inner_bwd, _site=bsite,
                        _static=bstatic):
                _site.record(_probe.sig_of((ct,) + arrays), _static)
                return _inner(ct, *arrays)

            fn = jax.jit(bwd_raw)
            bwd_jitted = True
        else:
            fn = bwd_raw
            bwd_jitted = False
        # backward compiles (often the larger cost) get the same
        # first-call compile attribution as the forward
        fn = _first_call_probe(f"{name}.bwd", key, fn, jitted=bwd_jitted)
        _fn_cache[key] = fn
    else:
        stat_add("op_cache_hit")
    return fn


def _attrs_key(attrs: dict):
    items = []
    for k in sorted(attrs):
        items.append((k, _const_key(attrs[k])))
    return tuple(items)


_amp_mod = None


def _amp():
    global _amp_mod
    if _amp_mod is None:
        from .. import amp as _amp_mod_  # deferred: amp imports tensor
        _amp_mod = _amp_mod_
    return _amp_mod


def call_op(name: str, *args, **attrs):
    """Execute a registered op eagerly on Tensors, recording the tape."""
    opdef = get_op(name)
    stat_add(f"op_count/{name}")
    if flag_value("FLAGS_check_shapes"):
        # InferMeta-style pre-dispatch validation (reference: phi/infermeta/
        # run per kernel launch); raises ShapeError at the call site instead
        # of an XLA error deep inside jit
        _infermeta_check(name, args, attrs)
    run = _call_op_timed if flag_value("FLAGS_benchmark") else _call_op_impl
    if _prof._active:
        # guarded so the inactive hot path pays ONE bool check, no span
        # object (perf-gate budget: tests/test_perf_gate.py)
        with _prof.record(f"op/{name}", "dispatch"):
            return run(name, opdef, args, attrs)
    return run(name, opdef, args, attrs)


def _call_op_timed(name, opdef, args, attrs):
    """FLAGS_benchmark per-op timing (reference flags.cc `benchmark`):
    blocks on the outputs, so debugging only."""
    import time
    t0 = time.perf_counter()
    out = _call_op_impl(name, opdef, args, attrs)
    try:
        jax.block_until_ready(jax.tree_util.tree_map(
            lambda t: t._data if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor)))
    except Exception:
        pass  # tracers under jit: timing is trace-time only
    # distribution, not a lossy sum: p50/p95/p99 per op via stat_histogram
    stat_observe(f"op_time_ms/{name}", (time.perf_counter() - t0) * 1e3)
    return out


def _call_op_impl(name, opdef, args, attrs):
    # Array-valued attrs (incl. Tensors and tracers) must be TRACED inputs,
    # never closure constants: the jit cache is keyed by structure only, so a
    # baked-in value would be served back for a different value of the same
    # shape (advisor finding r1).
    arr_attrs = {k: v for k, v in attrs.items()
                 if isinstance(v, (Tensor, jax.Array, np.ndarray))
                 or hasattr(v, "aval")}
    const_attrs = {k: v for k, v in attrs.items() if k not in arr_attrs}
    template, tensors = _unwrap_args(args)
    arr_attr_names = tuple(sorted(arr_attrs))
    for k in arr_attr_names:
        v = arr_attrs[k]
        tensors.append(v if isinstance(v, Tensor)
                       else Tensor(jnp.asarray(v), stop_gradient=True))
    arrays = [t._data for t in tensors]
    amp = _amp()
    if amp.is_auto_cast_enabled():
        arrays = amp.amp_cast_inputs(name, arrays)
    impl = opdef.select(args, attrs)
    akey = _attrs_key(const_attrs)
    fn = _get_callable(name, impl, template, akey, const_attrs,
                       arr_attr_names, jit_ok=opdef.jit)

    needs_grad = (is_grad_enabled() and not opdef.nondiff
                  and any(t._requires_grad() for t in tensors))

    # grads-on takes the SAME cached forward call as grads-off; the
    # pullback is a separate jit-cached recompute-backward bound lazily
    # (residual-free — backward re-linearizes inside its own jit)
    out = fn(*arrays)
    if needs_grad:
        bwd = _get_bwd_callable(name, impl, template, akey, fn,
                                arr_attr_names, jit_ok=opdef.jit)
        bound = tuple(arrays)

        def vjp_fn(ct, _bwd=bwd, _arrays=bound):
            return _bwd(ct, *_arrays)
    else:
        vjp_fn = None

    flat_out, out_treedef = jax.tree_util.tree_flatten(out)
    out_tensors = [Tensor(o, stop_gradient=not needs_grad)
                   for o in flat_out]

    if needs_grad:
        node = GradNode(
            op_name=name,
            vjp_fn=vjp_fn,
            inputs=tensors,
            n_outputs=len(flat_out),
            out_treedef=out_treedef,
            out_meta=[(o.shape, o.dtype) for o in flat_out],
        )
        for i, t in enumerate(out_tensors):
            t._node = node
            t._out_idx = i
            # integer outputs never carry grad
            if not jnp.issubdtype(t.dtype, jnp.floating) and \
               not jnp.issubdtype(t.dtype, jnp.complexfloating):
                t.stop_gradient = True

    if flag_value("FLAGS_check_nan_inf"):
        _check_nan_inf(name, out_tensors)

    if _capture.current is not None:
        # static-graph mode: append this dispatch to the active Program
        # (the append_op analog; see framework/static_capture.py)
        _capture.record(name, fn, tensors, out_tensors, const_attrs)

    return jax.tree_util.tree_unflatten(out_treedef, out_tensors)


def _check_nan_inf(name, out_tensors):
    """FLAGS_check_nan_inf analog (reference:
    framework/details/nan_inf_utils_detail.cc) — eager sweep of op outputs."""
    for t in out_tensors:
        if jnp.issubdtype(t.dtype, jnp.floating):
            try:
                bad = bool(jnp.any(~jnp.isfinite(t._data)))
            except Exception:
                return  # tracer — skip under jit
            if bad:
                raise FloatingPointError(
                    f"Operator {name} output contains NaN/Inf "
                    f"(tensor {t.name}, shape {t.shape})")


def to_array(x, dtype=None):
    """Coerce python/numpy/Tensor input to a jax array."""
    if isinstance(x, Tensor):
        a = x._data
        return a.astype(_dtypes.convert_dtype(dtype)) if dtype else a
    if dtype is not None:
        return jnp.asarray(x, dtype=_dtypes.convert_dtype(dtype))
    if isinstance(x, bool):
        return jnp.asarray(x)
    if isinstance(x, int):
        return jnp.asarray(x, dtype=jnp.int64)
    if isinstance(x, float):
        return jnp.asarray(x, dtype=_dtypes.get_default_dtype())
    return jnp.asarray(x)
