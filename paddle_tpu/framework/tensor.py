"""The Tensor facade and eager autograd tape.

TPU-native replacement for the reference's dygraph stack: ``DenseTensor``
(/root/reference/paddle/phi/core/dense_tensor.h:37) + eager autograd
(``egr::RunBackward`` paddle/fluid/eager/backward.cc:539, ``GradNodeBase``
eager/grad_node_info.h:162, ``TensorWrapper`` saved-tensor capture).

Design: a Tensor wraps a ``jax.Array``. Every eager op call goes through
:func:`paddle_tpu.framework.dispatch.call_op`, which (when grad is required)
obtains the op's VJP via ``jax.vjp`` and records one ``GradNode`` on a tape.
``Tensor.backward`` is a ready-queue topological walk over GradNodes — the
same shape as ``RunBackward``'s in-degree walk — except each node's backward
math is an XLA-compiled vjp closure rather than a hand-written CUDA grad
kernel. Saved forward residuals live inside the vjp closure (the
TensorWrapper analog) and are dropped after backward unless
``retain_graph=True``.

Under ``jax.jit`` tracing the same code paths work with tracer-backed
Tensors, which is how the jitted train-step path (hapi / fleet) reuses the
eager op library without a separate "static" op set.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes as _dtypes
from .enforce import InvalidArgumentError, PreconditionNotMetError

_no_grad_state = threading.local()


def is_grad_enabled() -> bool:
    return not getattr(_no_grad_state, "off", False)


@contextlib.contextmanager
def grad_enabled_guard(mode: bool):
    """Set grad recording to ``mode`` unconditionally (True re-enables
    inside an enclosing no_grad scope — reference set_grad_enabled)."""
    old = getattr(_no_grad_state, "off", False)
    _no_grad_state.off = not mode
    try:
        yield
    finally:
        _no_grad_state.off = old


@contextlib.contextmanager
def no_grad_guard():
    old = getattr(_no_grad_state, "off", False)
    _no_grad_state.off = True
    try:
        yield
    finally:
        _no_grad_state.off = old


class no_grad:
    """``paddle.no_grad`` — usable as context manager and decorator."""

    def __enter__(self):
        self._cm = no_grad_guard()
        return self._cm.__enter__()

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)

    def __call__(self, fn):
        def wrapper(*a, **k):
            with no_grad_guard():
                return fn(*a, **k)

        return wrapper


class GradNode:
    """One recorded op on the tape (analog of a codegen'd GradNode)."""

    __slots__ = ("op_name", "vjp_fn", "inputs", "n_outputs", "out_treedef",
                 "out_meta", "out_hooks", "retained", "__weakref__")

    def __init__(self, op_name, vjp_fn, inputs, n_outputs, out_treedef,
                 out_meta):
        self.op_name = op_name
        self.vjp_fn = vjp_fn
        self.inputs: List[Tensor] = inputs
        self.n_outputs = n_outputs
        self.out_treedef = out_treedef
        self.out_meta = out_meta  # [(shape, dtype)] per flat output
        self.out_hooks = None  # {out_idx: [fn]} — Tensor.register_hook
        self.retained = None   # {out_idx: weakref(Tensor)} — retain_grads


def _is_float_dtype(dt) -> bool:
    return jnp.issubdtype(dt, jnp.floating) or jnp.issubdtype(
        dt, jnp.complexfloating)


class Tensor:
    """Eager tensor over a jax.Array."""

    # NO __dict__: the hottest object in the system keeps the memory and
    # attribute-safety benefits slots exist for (r3 verdict weak #8).
    # Framework-known dynamic attrs are explicit slots; they may be unset
    # (readers use getattr(..., default)).
    __slots__ = ("_data", "stop_gradient", "grad", "_node", "_out_idx",
                 "name", "persistable", "_retain_grads", "_grad_hooks",
                 "optimize_attr", "regularizer", "need_clip", "mesh_axes",
                 "__weakref__")

    _next_id = 0

    def __init__(self, data, stop_gradient: bool = True,
                 name: Optional[str] = None):
        self._data = data  # jax.Array or tracer
        self.stop_gradient = stop_gradient
        self.grad: Optional[Tensor] = None
        self._node: Optional[GradNode] = None
        self._out_idx = 0
        self._retain_grads = False
        self.persistable = False
        if name is None:
            name = f"generated_tensor_{Tensor._next_id}"
            Tensor._next_id += 1
        self.name = name

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def is_leaf(self) -> bool:
        return self._node is None

    @property
    def place(self):
        from .place import current_place
        return current_place()

    def _requires_grad(self) -> bool:
        return ((not self.stop_gradient) or self._node is not None) \
            and _is_float_dtype(self._data.dtype)

    # -- conversion ---------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def item(self):
        return self._data.item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def __float__(self):
        # any 1-element tensor converts (reference semantics), not just rank-0
        return float(self._data.reshape(()) if self._data.size == 1
                     else self._data)

    def __int__(self):
        return int(self._data.reshape(()) if self._data.size == 1
                   else self._data)

    def __bool__(self):
        return bool(self._data.reshape(()) if self._data.size == 1
                    else self._data)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._data.shape[0]

    def __repr__(self):
        try:
            body = np.array2string(np.asarray(self._data), precision=8,
                                   separator=", ")
        except Exception:  # tracers
            body = repr(self._data)
        return (f"Tensor(shape={self.shape}, dtype={self._data.dtype}, "
                f"stop_gradient={self.stop_gradient},\n       {body})")

    # -- autograd -----------------------------------------------------------
    def retain_grads(self):
        self._retain_grads = True
        if self._node is not None:
            import weakref
            if self._node.retained is None:
                self._node.retained = {}
            self._node.retained[self._out_idx] = weakref.ref(self)

    def backward(self, grad_tensor=None, retain_graph: bool = False):
        run_backward(self, grad_tensor, retain_graph)

    def register_hook(self, hook):
        """Reference: varbase register_hook — ``hook(grad) -> grad|None``
        runs when this tensor's gradient is computed during backward,
        on the FULLY-ACCUMULATED gradient (all consuming paths summed),
        for leaves and non-leaves alike."""
        if self.stop_gradient and self._node is None:
            raise ValueError(
                "register_hook on a tensor with stop_gradient=True")
        if self._node is not None:
            if self._node.out_hooks is None:
                self._node.out_hooks = {}
            hooks = self._node.out_hooks.setdefault(self._out_idx, [])
        else:
            hooks = getattr(self, "_grad_hooks", None)
            if hooks is None:
                hooks = self._grad_hooks = []
        hooks.append(hook)

        class _Remove:
            def remove(self_inner):
                if hook in hooks:
                    hooks.remove(hook)

        return _Remove()

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        # lax.stop_gradient, not just a tape-less rewrap: inside a jax-
        # traced step (hapi donated train step, static replay) gradients
        # are jax's, which ignore the eager stop_gradient flag — without
        # the primitive a detached path trains under fit() while being
        # frozen under eager backward() (divergence found by the
        # dead-grad analysis pass, tests/test_analysis.py)
        import jax
        return Tensor(jax.lax.stop_gradient(self._data),
                      stop_gradient=True)

    def clone(self) -> "Tensor":
        from .dispatch import call_op
        return call_op("assign", self)

    def _rebind(self, new_value: "Tensor"):
        """In-place mutation: take over another tensor's value and tape
        position (used by setitem / *_ ops).

        The recording op's ``inputs`` list references *this* object; once we
        point ``self._node`` at that op, backward would route this input's
        cotangent to the op itself (a cycle) and drop the upstream graph. So
        the node's references to ``self`` are swapped for a snapshot tensor
        carrying the pre-mutation tape position.
        """
        node = new_value._node
        if node is not None:
            if self._node is None and not self.stop_gradient:
                raise PreconditionNotMetError(
                    "in-place modification of a leaf tensor that requires "
                    "grad; wrap the mutation in paddle.no_grad() or operate "
                    "on a non-leaf result")
            snapshot = None
            for i, t in enumerate(node.inputs):
                if t is self:
                    if snapshot is None:
                        snapshot = Tensor(self._data,
                                          stop_gradient=self.stop_gradient)
                        snapshot._node = self._node
                        snapshot._out_idx = self._out_idx
                        snapshot._retain_grads = self._retain_grads
                    node.inputs[i] = snapshot
        self._data = new_value._data
        self._node = node
        self._out_idx = new_value._out_idx
        self.stop_gradient = new_value.stop_gradient

    # pytree: allow Tensors to appear directly in jitted function args
    def __jax_array__(self):
        return self._data


def _flatten_tensor(t: Tensor):
    return (t._data,), (t.stop_gradient,)


def _unflatten_tensor(aux, children):
    return Tensor(children[0], stop_gradient=aux[0])


jax.tree_util.register_pytree_node(Tensor, _flatten_tensor, _unflatten_tensor)


class Parameter(Tensor):
    """Trainable tensor (analog of framework::Parameter /
    egr::GradNodeAccumulation leaves)."""

    def __init__(self, data, name=None, trainable=True):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.persistable = True

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


# ---------------------------------------------------------------------------
# backward engine (analog of egr::RunBackward, eager/backward.cc:539)
# ---------------------------------------------------------------------------

def run_backward(root: Tensor, grad_tensor=None, retain_graph=False):
    if root._node is None:
        if root.stop_gradient:
            raise PreconditionNotMetError(
                "backward() on a tensor with no grad graph")
        return  # leaf: nothing to do
    if grad_tensor is None:
        if root.size != 1:
            raise InvalidArgumentError(
                "grad_tensor must be provided for non-scalar backward()")
        seed = jnp.ones(root._data.shape, root._data.dtype)
    else:
        seed = grad_tensor._data if isinstance(grad_tensor, Tensor) \
            else jnp.asarray(grad_tensor)

    # topological order via iterative DFS
    topo: List[GradNode] = []
    state = {}  # id(node) -> 0 visiting / 1 done
    stack = [(root._node, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            state[id(node)] = 1
            topo.append(node)
            continue
        if id(node) in state:
            continue
        state[id(node)] = 0
        stack.append((node, True))
        for t in node.inputs:
            if t._node is not None and id(t._node) not in state:
                stack.append((t._node, False))

    # cotangent accumulation per (node, out_idx)
    cots = {id(root._node): [None] * root._node.n_outputs}
    cots[id(root._node)][root._out_idx] = seed
    leaf_acc = {}  # id(leaf) -> (leaf, summed grad) for hooked leaves

    for node in reversed(topo):
        pending = cots.pop(id(node), None)
        if pending is None or all(c is None for c in pending):
            continue
        if node.out_hooks:
            # user grad hooks on this node's outputs see the accumulated
            # cotangent and may replace it (reference register_hook)
            for i, hook_list in node.out_hooks.items():
                if pending[i] is None:
                    continue
                for hook in hook_list:
                    res = hook(Tensor(pending[i], stop_gradient=True))
                    if res is not None:
                        pending[i] = res._data if isinstance(res, Tensor) \
                            else jnp.asarray(res)
        if node.retained:
            # retain_grads accumulation happens HERE, after hooks, on the
            # final cotangent — consistent with what downstream receives
            for i, tref in node.retained.items():
                t = tref()
                if t is not None and pending[i] is not None:
                    _accum_grad(t, pending[i])
        if node.vjp_fn is None:
            raise PreconditionNotMetError(
                f"grad graph for op {node.op_name!r} was already freed; "
                "pass retain_graph=True to backward() to reuse it")
        # Cast each cotangent to the node's recorded output dtype: AMP
        # boundaries (white-listed bf16 op feeding a black-listed f32 op)
        # otherwise hand the pullback a cotangent of the wrong dtype.
        flat_cots = [
            (c.astype(dtype) if getattr(c, "dtype", dtype) != dtype else c)
            if c is not None else jnp.zeros(shape, dtype)
            for c, (shape, dtype) in zip(pending, node.out_meta)
        ]
        out_cot = jax.tree_util.tree_unflatten(node.out_treedef, flat_cots)
        in_grads = node.vjp_fn(out_cot)
        if not retain_graph:
            node.vjp_fn = None
        for t, g in zip(node.inputs, in_grads):
            if g is None or not _is_float_dtype(
                    jnp.result_type(getattr(g, "dtype", jnp.float32))):
            # float0 cotangents come back for int inputs — skip them
                continue
        # distribute
            if t._node is not None:
                slot = cots.setdefault(id(t._node), [None] * t._node.n_outputs)
                slot[t._out_idx] = g if slot[t._out_idx] is None \
                    else slot[t._out_idx] + g
            elif not t.stop_gradient:
                # leaves: accumulate per path; hooks run ONCE at the end on
                # the summed gradient (reference semantics for multi-use
                # leaves like tied embeddings)
                if getattr(t, "_grad_hooks", None):
                    acc = leaf_acc.get(id(t))
                    leaf_acc[id(t)] = (t, g if acc is None
                                       else acc[1] + g)
                else:
                    _accum_grad(t, g)

    _flush_hooked_leaves(leaf_acc)


def _flush_hooked_leaves(leaf_acc):
    for t, g in leaf_acc.values():
        for hook in getattr(t, "_grad_hooks", None) or ():
            res = hook(Tensor(g, stop_gradient=True))
            if res is not None:
                g = res._data if isinstance(res, Tensor) \
                    else jnp.asarray(res)
        _accum_grad(t, g)


def _accum_grad(t: Tensor, g):
    # master-weight semantics: the leaf's grad carries the leaf's dtype even
    # when the op ran in a lower AMP precision
    if hasattr(g, "astype") and g.dtype != t._data.dtype and \
            _is_float_dtype(t._data.dtype):
        g = g.astype(t._data.dtype)
    if t.grad is None:
        t.grad = Tensor(g, stop_gradient=True, name=t.name + "@GRAD")
    else:
        t.grad._data = t.grad._data + g
