"""Compiled-program registry: what did each jit site cost to compile,
and what does one execution of it cost in FLOPs and bytes.

Reference analog: ``paddle.flops`` / the profiler's per-program tables —
the reference hand-counts per-layer FLOPs (hapi/dynamic_flops.py); here
the compiler already knows, so every jit site the framework OWNS (the
eager-op dispatch wrappers, the hapi donated train step, the serving
prefill/decode steps per bucket) registers its compiled executable here
at compile time:

* **compile cost** — wall ms per compile into the ``compile/ms`` and
  ``compile/ms/<site>`` histograms plus the ``compile/count`` counter
  (framework/monitor.py), so compile churn is a queryable distribution,
  not a feeling;
* **program cost** — jaxpr eqn count, XLA ``cost_analysis()`` FLOPs and
  bytes-accessed, and ``memory_analysis()`` temp/argument/output bytes,
  wherever the backend provides them (CPU provides cost analysis; a
  backend without it records ``None``, never a fake number).

From these, ``Model.fit`` derives achieved FLOP/s and MFU per flush
window (``hapi/flops_per_sec`` / ``hapi/mfu``, surfaced in the ProgBar)
and ``GenerationEngine.stats()`` derives model-FLOPs-per-token and
serving MFU — against :func:`peak_flops`, a per-device-kind peak table
overridable with ``PADDLE_TPU_PEAK_FLOPS`` (CPU has no honest peak, so
without the override only raw FLOP/s are reported).

Two integration shapes:

* :func:`aot_site` — wraps a function the way ``jax.jit`` would, but
  compiles EXPLICITLY (``trace → lower → compile``) per signature and
  calls the held executable directly. This is how the few big owned
  sites (train step, serving steps) register full cost analysis with
  exactly ONE XLA compile — jax 0.4.x does NOT share its jit dispatch
  cache with ``lower().compile()``, so querying analysis lazily from a
  normally-jitted function would compile everything twice.
* :func:`note_compile` — a timing-only note for sites where the jit
  cache must stay jax-owned (the eager op dispatch layer times its
  cache-miss first call — trace+compile+first run — and notes it here).

:func:`analyze_callable` is the one-shot helper ``cost_model.
estimate_flops`` and ``hapi.model_summary.flops`` dedupe onto.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

from .monitor import stat_add, stat_observe

__all__ = ["ProgramRecord", "aot_site", "AotSite", "note_compile", "get",
           "snapshot", "reset", "analyze_compiled", "analyze_callable",
           "peak_flops", "PEAK_FLOPS_TABLE"]

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_records: Dict[str, "ProgramRecord"] = {}
# same bound discipline as trace_probe: a notebook sweep creating
# thousands of Models must not grow host memory without bound; past the
# cap records still accumulate for callers holding them by reference,
# only snapshot() visibility is bounded
_MAX_RECORDS = 1024

# bf16 peak FLOPs/sec per chip by device-kind substring (the bench.py
# table, hoisted here so fit()/stats() MFU and the bench children agree
# on one source). Override with PADDLE_TPU_PEAK_FLOPS (a float) — the
# escape hatch for unlisted chips AND the pinned fake peak the tests and
# bench.py --dry-run use to exercise the MFU math on CPU.
PEAK_FLOPS_TABLE = (
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
    ("v4", 275e12), ("v3", 123e12), ("v6", 918e12),
)


class ProgramRecord:
    """Per-site compile + cost bookkeeping (host ints/floats only)."""

    __slots__ = ("site", "compiles", "compile_ms_total", "last_compile_ms",
                 "eqns", "flops", "bytes_accessed", "temp_bytes",
                 "argument_bytes", "output_bytes", "generated_code_bytes",
                 "static_peak_bytes")

    def __init__(self, site: str):
        self.site = site
        self.compiles = 0
        self.compile_ms_total = 0.0
        self.last_compile_ms: Optional[float] = None
        self.eqns: Optional[int] = None
        self.flops: Optional[float] = None
        self.bytes_accessed: Optional[float] = None
        self.temp_bytes: Optional[int] = None
        self.argument_bytes: Optional[int] = None
        self.output_bytes: Optional[int] = None
        self.generated_code_bytes: Optional[int] = None
        # ISSUE 18: the donation-aware jaxpr liveness estimate, recorded
        # at trace time NEXT TO the XLA memory figures so the dry-run
        # can cross-check the static planner against the backend
        self.static_peak_bytes: Optional[int] = None

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return (f"<ProgramRecord {self.site!r} compiles={self.compiles} "
                f"flops={self.flops} eqns={self.eqns}>")


def _record(site: str) -> ProgramRecord:
    with _lock:
        r = _records.get(site)
        if r is None:
            r = ProgramRecord(site)
            if len(_records) < _MAX_RECORDS:
                _records[site] = r
        return r


def note_compile(site: str, wall_ms: float, eqns: Optional[int] = None,
                 analysis: Optional[dict] = None) -> ProgramRecord:
    """Record one compile of ``site``: wall ms into the ``compile/ms``
    histograms (global + per-site), ``compile/count``, and — when the
    caller has them — the program's eqn count and cost/memory analysis
    onto the site's :class:`ProgramRecord` (latest compile wins: a
    retrace at a new shape supersedes the old figures)."""
    rec = _record(site)
    with _lock:
        rec.compiles += 1
        rec.compile_ms_total += float(wall_ms)
        rec.last_compile_ms = float(wall_ms)
        if eqns is not None:
            rec.eqns = int(eqns)
        if analysis:
            for k in ("flops", "bytes_accessed", "temp_bytes",
                      "argument_bytes", "output_bytes",
                      "generated_code_bytes", "static_peak_bytes"):
                if analysis.get(k) is not None:
                    setattr(rec, k, analysis[k])
        registered = _records.get(site) is rec
    stat_add("compile/count")
    stat_observe("compile/ms", float(wall_ms))
    if registered:
        # per-site histograms only for REGISTERED sites: names are
        # per-instance (one per Model / engine), and monitor histograms
        # have no name cap of their own — past _MAX_RECORDS the
        # per-site series would be exactly the unbounded host-memory
        # growth the record cap exists to prevent
        stat_observe(f"compile/ms/{site}", float(wall_ms))
    return rec


def get(site: str) -> Optional[ProgramRecord]:
    with _lock:
        return _records.get(site)


def snapshot() -> Dict[str, dict]:
    with _lock:
        return {name: r.as_dict() for name, r in _records.items()}


def reset() -> None:
    with _lock:
        _records.clear()


# ---------------------------------------------------------------------------
# cost/memory analysis of a compiled executable
# ---------------------------------------------------------------------------

def analyze_compiled(compiled) -> dict:
    """Tolerant cost+memory query of an XLA ``Compiled`` (or anything
    shaped like one). Every field is ``None`` where the backend provides
    no answer — never ``-1`` or another fake number a dashboard would
    chart as real."""
    out: Dict[str, Any] = {"flops": None, "bytes_accessed": None,
                           "temp_bytes": None, "argument_bytes": None,
                           "output_bytes": None,
                           "generated_code_bytes": None}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if ca:
            f = ca.get("flops")
            # XLA reports -1 for "unknown" on some backends — that is
            # the silent-(-1.0) bug class this registry exists to kill
            if f is not None and f >= 0:
                out["flops"] = float(f)
            b = ca.get("bytes accessed")
            if b is not None and b >= 0:
                out["bytes_accessed"] = float(b)
    except Exception as e:                               # noqa: BLE001
        logger.debug("cost_analysis unavailable: %r", e)
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            for field, key in (("temp_size_in_bytes", "temp_bytes"),
                               ("argument_size_in_bytes", "argument_bytes"),
                               ("output_size_in_bytes", "output_bytes"),
                               ("generated_code_size_in_bytes",
                                "generated_code_bytes")):
                v = getattr(ma, field, None)
                if v is not None:
                    out[key] = int(v)
    except Exception as e:                               # noqa: BLE001
        logger.debug("memory_analysis unavailable: %r", e)
    return out


def static_peak_of_trace(closed_jaxpr, donated_mask=None) -> Optional[int]:
    """Donation-aware liveness peak of an already-traced program
    (analysis/liveness.py), or ``None`` when the scan cannot run —
    same honesty contract as :func:`analyze_compiled`: never a fake
    number. Host arithmetic over avals; no compile, no device."""
    try:
        from ..analysis.liveness import jaxpr_liveness
        return int(jaxpr_liveness(closed_jaxpr,
                                  donated_mask).static_peak_bytes)
    except Exception as e:                               # noqa: BLE001
        logger.debug("static liveness unavailable: %r", e)
        return None


def analyze_callable(fn, *example_args, static_argnums=(),
                     site: Optional[str] = None) -> Optional[dict]:
    """Trace+compile ``fn`` on ``example_args`` and return its program
    cost: ``{"flops", "bytes_accessed", "eqns", "temp_bytes", ...}``
    (fields ``None`` where the backend has no analysis). Returns ``None``
    when even tracing/compiling fails. The ONE helper behind
    ``cost_model.estimate_flops`` and ``hapi.model_summary.flops`` — the
    hand-rolled ``lower().compile().cost_analysis()`` snippets they used
    to duplicate live here now. Registers under ``site`` when given."""
    import jax
    try:
        jitted = fn if hasattr(fn, "lower") else \
            jax.jit(fn, static_argnums=static_argnums)
        t0 = time.perf_counter()
        eqns = None
        static_peak = None
        try:
            traced = jitted.trace(*example_args)
            eqns = len(traced.jaxpr.jaxpr.eqns)
            static_peak = static_peak_of_trace(traced.jaxpr)
            compiled = traced.lower().compile()
        except AttributeError:
            # older jax without .trace(): lower directly, skip eqn count
            compiled = jitted.lower(*example_args).compile()
        wall_ms = (time.perf_counter() - t0) * 1e3
    except Exception as e:                               # noqa: BLE001
        logger.debug("analyze_callable: trace/compile failed: %r", e)
        return None
    analysis = analyze_compiled(compiled)
    analysis["eqns"] = eqns
    analysis["static_peak_bytes"] = static_peak
    if site is not None:
        note_compile(site, wall_ms, eqns=eqns, analysis=analysis)
    return analysis


# ---------------------------------------------------------------------------
# peak FLOPs / MFU
# ---------------------------------------------------------------------------

def peak_flops(device_kind: Optional[str] = None) -> Optional[float]:
    """Peak FLOP/s of one chip of the current (or named) device kind.
    ``PADDLE_TPU_PEAK_FLOPS`` (a float) overrides everything — the knob
    for unlisted chips and for pinning a fake peak in tests. ``None``
    when nothing applies (CPU: report FLOP/s, never a made-up MFU)."""
    env = os.environ.get("PADDLE_TPU_PEAK_FLOPS", "").strip()
    if env:
        try:
            v = float(env)
            if v > 0:
                return v
        except ValueError:
            logger.debug("bad PADDLE_TPU_PEAK_FLOPS=%r ignored", env)
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:                                # noqa: BLE001
            return None
    dk = str(device_kind).lower()
    for sub, peak in PEAK_FLOPS_TABLE:
        if sub in dk:
            return peak
    return None


# ---------------------------------------------------------------------------
# AOT sites: explicit compile-and-call for the big owned programs
# ---------------------------------------------------------------------------

def _static_value_key(v):
    """Value key for a static argument: (type, value) for hashables —
    1 == 1.0 == True must not alias, same rule as the dispatch layer's
    _const_key — repr for the rest."""
    try:
        hash(v)
    except TypeError:
        return ("repr", repr(v))
    return (type(v).__name__, v)


# process-wide trace serialization (see AotSite._compile): tracing a
# step body that reads live Layer state (functional_state) is not
# thread-safe across sites sharing one network; RLock because a traced
# body may legitimately re-enter another AotSite under a tracer
_TRACE_LOCK = threading.RLock()


class AotSite:
    """A jit site that owns its executables: per input signature it
    traces, lowers and compiles EXPLICITLY (timing the compile and
    registering the program's cost analysis), then dispatches straight
    to the held executable — drop-in for ``jax.jit(fn, static_argnums,
    donate_argnums)`` at sites whose signatures are flat and stable (the
    donated train step, the serving prefill/decode steps).

    Transparent under tracing: called with tracers (``analysis.analyze``,
    a ``make_jaxpr`` of an outer program), it delegates to the inner
    jitted function, so the pjit eqn — donation contract included —
    appears in the outer trace exactly as before.

    Any failure of the explicit path (a backend without AOT support, an
    un-flattenable argument) falls back PERMANENTLY to the plain jitted
    call for this site, still noting first-call wall time — robustness
    first, cost analysis when available.
    """

    _MAX_SIGNATURES = 64     # executables kept per site (oldest evicted)

    def __init__(self, name: str, fn, static_argnums=(), donate_argnums=()):
        import jax
        self.site = name
        self.static_argnums = tuple(int(i) for i in static_argnums)
        self.donate_argnums = tuple(int(i) for i in donate_argnums)
        self.jitted = jax.jit(fn, static_argnums=self.static_argnums or
                              None, donate_argnums=donate_argnums)
        self.record = _record(name)
        self._compiled: Dict[Tuple, Any] = {}
        self._flops_by_key: Dict[Tuple, Optional[float]] = {}
        # FLOPs of the program the LAST __call__ dispatched — the
        # record's .flops is latest-compile-wins, so a caller averaging
        # cost over many dispatches (fit's MFU, serving stats) must read
        # this per-dispatch value or a partial last batch would be
        # billed at the wrong program's cost
        self.last_dispatch_flops: Optional[float] = None
        self._fallback = False
        self._seen_fallback_keys: set = set()

    # -- key building ------------------------------------------------------
    def _key(self, args):
        """(signature, tracer?) of the call: per-leaf (shape, dtype) for
        dynamic arrays, the VALUE for static-position args — statics
        select the compiled program exactly as jit's static_argnums do,
        so an array-typed static (np.int32(3) vs np.int32(4): same
        shape/dtype, different program!) must never fall into the
        shape-keyed path."""
        import jax
        statics = tuple(
            (i, _static_value_key(args[i])) for i in self.static_argnums
            if i < len(args))
        leaves, treedef = jax.tree_util.tree_flatten(self._dynamic(args))
        parts = []
        tracer = False
        for leaf in leaves:
            if isinstance(leaf, jax.core.Tracer):
                tracer = True
                break
            shape = getattr(leaf, "shape", None)
            if shape is not None:
                parts.append((tuple(shape), str(leaf.dtype)))
            else:
                parts.append(("py", _static_value_key(leaf)))
        return (statics, treedef, tuple(parts)), tracer

    def _dynamic(self, args):
        if not self.static_argnums:
            return args
        drop = set(self.static_argnums)
        return tuple(a for i, a in enumerate(args) if i not in drop)

    # -- dispatch ----------------------------------------------------------
    def __call__(self, *args):
        # per-call cost: one tree_flatten + a (shape, dtype) tuple per
        # leaf — tens of µs for a full train-state tree against the
        # multi-ms step it dispatches. A cheaper identity probe (leaf
        # count + first-leaf aval) could serve the wrong program when a
        # LATER leaf changes shape, so the full key stays.
        try:
            key, tracer = self._key(args)
        except Exception:                                # noqa: BLE001
            self._fallback = True
            key, tracer = None, False
        if tracer:
            # under an outer trace the executable cannot run: inline the
            # jitted call so the pjit eqn lands in the outer jaxpr
            return self.jitted(*args)
        if self._fallback or key is None:
            return self._call_fallback(key, args)
        compiled = self._compiled.get(key)
        if compiled is None:
            compiled = self._compile(key, args)
            if compiled is None:             # explicit path unavailable
                return self._call_fallback(key, args)
        self.last_dispatch_flops = self._flops_by_key.get(key)
        return compiled(*self._dynamic(args))

    def _donated_mask(self, args):
        """Donation mask over the traced program's flat invars: the
        jitted fn's dynamic args in order, each arg's leaves marked by
        whether its ORIGINAL argnum (static args counted, per jax.jit
        semantics) is donated."""
        if not self.donate_argnums:
            return None
        import jax
        try:
            mask = []
            for i, a in enumerate(args):
                if i in self.static_argnums:
                    continue
                n = len(jax.tree_util.tree_leaves(a))
                mask.extend([i in self.donate_argnums] * n)
            return mask
        except Exception:                                # noqa: BLE001
            return None

    def _compile(self, key, args):
        t0 = time.perf_counter()
        try:
            # ONE trace at a time, process-wide: the serving/hapi step
            # bodies trace through functional_state(net, ...), which
            # temporarily rebinds the network's layer state — two
            # engine scheduler threads tracing over a SHARED model
            # concurrently corrupt each other's captures ("compiled for
            # 79 inputs but called with 43", then a backend abort).
            # Compiles are rare and the executable DISPATCH below stays
            # outside the lock, so fleets serialize only their cold
            # start.
            with _TRACE_LOCK:
                traced = self.jitted.trace(*args)
                eqns = len(traced.jaxpr.jaxpr.eqns)
                compiled = traced.lower().compile()
        except Exception as e:                           # noqa: BLE001
            logger.debug("AotSite %s: explicit compile failed (%r); "
                         "falling back to plain jit", self.site, e)
            self._fallback = True
            return None
        wall_ms = (time.perf_counter() - t0) * 1e3
        analysis = analyze_compiled(compiled)
        analysis["static_peak_bytes"] = static_peak_of_trace(
            traced.jaxpr, self._donated_mask(args))
        note_compile(self.site, wall_ms, eqns=eqns, analysis=analysis)
        if len(self._compiled) >= self._MAX_SIGNATURES:
            oldest = next(iter(self._compiled))
            self._compiled.pop(oldest)
            self._flops_by_key.pop(oldest, None)
        self._compiled[key] = compiled
        self._flops_by_key[key] = analysis.get("flops")
        return compiled

    def _call_fallback(self, key, args):
        """Plain jitted call; first call per signature still timed and
        noted (trace+compile+first-run wall — the dispatch-layer
        approximation) so ``compile/ms``/``compile/count`` stay live."""
        first = key is not None and key not in self._seen_fallback_keys
        t0 = time.perf_counter()
        if first:
            # same shared-model trace race as _compile: the first call
            # per signature is the one that traces
            with _TRACE_LOCK:
                out = self.jitted(*args)
        else:
            out = self.jitted(*args)
        if first:
            self._seen_fallback_keys.add(key)
            note_compile(self.site, (time.perf_counter() - t0) * 1e3)
        # best effort on the fallback path: latest-compile figures
        self.last_dispatch_flops = self.record.flops
        return out

    def __repr__(self):
        return (f"<AotSite {self.site!r} signatures={len(self._compiled)} "
                f"fallback={self._fallback}>")


def aot_site(name: str, fn, static_argnums=(), donate_argnums=()) -> AotSite:
    """Build an :class:`AotSite` — the registry-instrumented replacement
    for ``jax.jit(fn, static_argnums=..., donate_argnums=...)`` at owned
    program sites."""
    return AotSite(name, fn, static_argnums=static_argnums,
                   donate_argnums=donate_argnums)
