"""Persistent XLA compilation cache (framework-level).

The first jit of a heavy graph costs seconds to minutes (GPT-2 through a
busy TPU relay has eaten most of a 300 s bench child on compiles alone —
bench.py's robustness notes). XLA can serialize compiled executables to
disk and reload them keyed on (HLO, compile options, jaxlib version), so
every process after the first skips the compile entirely. This module
wires jax's knobs for that behind the framework flag surface and parks
the entries under the same ``~/.cache/paddle_tpu/`` root the autotune
cache uses (ops/autotune_cache.py), so one directory carries all
persistent per-machine tuning state.

Usage::

    FLAGS_compile_cache=1 python train.py          # env-seeded, or
    paddle.set_flags({"FLAGS_compile_cache": True}) # before jits, then
    compile_cache.enable()                          # explicit form

``enable()`` is called automatically at package import when
``FLAGS_compile_cache`` is set (framework/__init__.py), and by
``bench.py`` for every child so repeat benchmark runs skip recompiles.
Every jax knob is feature-tested with ``hasattr`` — on a jax build
without the persistent cache this degrades to a clean no-op recorded in
``status()["reason"]``, never an AttributeError.

Reference analog: the reference caches serialized CUDA autotune/program
state per machine; jax's compilation cache is the XLA-era equivalent.
"""
from __future__ import annotations

import os
import threading
from typing import Optional

from .flags import flag_value

__all__ = ["cache_root", "default_dir", "enable", "disable", "status",
           "entries", "maybe_enable"]

_lock = threading.Lock()
_state = {"enabled": False, "dir": None, "reason": None}


def cache_root() -> str:
    """The per-user persistent cache root shared by every paddle_tpu
    cache family (autotune entries, XLA executables). Override with
    ``PADDLE_TPU_CACHE_ROOT``."""
    return os.environ.get(
        "PADDLE_TPU_CACHE_ROOT",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu"))


def default_dir() -> str:
    """Where XLA executables land when no explicit dir is configured:
    ``FLAGS_compile_cache_dir``, else jax's own ``JAX_COMPILATION_CACHE_DIR``
    env (native jax deployments keep working), else
    ``<cache_root()>/xla_cache``."""
    return flag_value("FLAGS_compile_cache_dir") or \
        os.environ.get("JAX_COMPILATION_CACHE_DIR") or \
        os.path.join(cache_root(), "xla_cache")


def enable(cache_dir: Optional[str] = None,
           min_compile_time_secs: Optional[float] = None) -> bool:
    """Turn the persistent cache on for this process. Returns True when
    jax accepted the configuration; False (with ``status()["reason"]``
    set) when the installed jax has no cache support or the directory is
    unwritable. Safe to call repeatedly; the last dir wins.

    ``min_compile_time_secs``: only compiles at least this long are
    persisted. None keeps jax's own floor (~1 s) — the right production
    default: micro-compiles cost more to serialize than to redo and
    would grow the dir without bound. Pass 0 to persist everything
    (tests, the dry-run canary, tiny-model runs)."""
    d = cache_dir or default_dir()
    try:
        import jax
    except Exception as e:  # pragma: no cover - jax is a hard dep
        with _lock:
            _state.update(enabled=False, reason=f"jax import failed: {e}")
        return False
    if not hasattr(jax.config, "jax_compilation_cache_dir"):
        with _lock:
            _state.update(
                enabled=False,
                reason="this jax has no jax_compilation_cache_dir knob")
        return False
    try:
        os.makedirs(d, exist_ok=True)
    except OSError as e:
        if cache_dir is not None:
            # an EXPLICITLY requested dir fails honestly
            with _lock:
                _state.update(enabled=False,
                              reason=f"cache dir unwritable: {e}")
            return False
        # default root unwritable (read-only HOME in CI containers):
        # fall back to a PER-UID tmp dir so bench children still skip
        # recompiles — worse persistence beats silently losing the cache.
        # The uid suffix + ownership check prevent another local user
        # pre-creating the path and feeding us poisoned serialized
        # executables (jax deserializes whatever it finds there).
        import tempfile
        uid = getattr(os, "getuid", lambda: "u")()
        d = os.path.join(tempfile.gettempdir(),
                         f"paddle_tpu_xla_cache_{uid}")
        try:
            os.makedirs(d, exist_ok=True)
            if hasattr(os, "getuid") and os.stat(d).st_uid != os.getuid():
                raise OSError(f"{d} is owned by another user")
        except OSError as e2:
            with _lock:
                _state.update(enabled=False,
                              reason=f"cache dir unwritable: {e2}")
            return False
    jax.config.update("jax_compilation_cache_dir", d)
    # knobs that exist on newer jaxes only — each individually optional
    knobs = [("jax_enable_compilation_cache", True)]  # master switch
    #          (default True, but a prior disable() must be reversible)
    if min_compile_time_secs is not None:
        knobs += [("jax_persistent_cache_min_compile_time_secs",
                   float(min_compile_time_secs)),
                  ("jax_persistent_cache_min_entry_size_bytes", 0)]
    for knob, val in knobs:
        if hasattr(jax.config, knob):
            jax.config.update(knob, val)
    _reset_jax_cache_module()
    with _lock:
        _state.update(enabled=True, dir=d, reason=None)
    return True


def _reset_jax_cache_module() -> None:
    """jax's compilation_cache initializes AT MOST ONCE per process: if
    any jit ran before enable() (cache dir unset at the time), the module
    latched 'disabled' and config updates are silently ignored. Reset it
    so the next compile re-initializes against the new settings."""
    try:
        from jax._src import compilation_cache as _jcc
        if hasattr(_jcc, "reset_cache"):
            _jcc.reset_cache()
    except Exception:  # private-API drift: stay best-effort
        pass


def disable() -> None:
    """Stop persisting (already-written entries stay on disk)."""
    try:
        import jax
        if hasattr(jax.config, "jax_compilation_cache_dir"):
            jax.config.update("jax_compilation_cache_dir", None)
        _reset_jax_cache_module()
    except Exception:
        pass
    with _lock:
        _state.update(enabled=False, reason="disabled")


def status() -> dict:
    with _lock:
        return dict(_state)


def entries(cache_dir: Optional[str] = None) -> int:
    """Number of serialized-executable entries on disk (``-cache`` files
    when jax names them that way, else all regular files)."""
    d = cache_dir or _state["dir"] or default_dir()
    try:
        names = os.listdir(d)
    except OSError:
        return 0
    cache_files = [n for n in names if n.endswith("-cache")]
    return len(cache_files) if cache_files else \
        sum(os.path.isfile(os.path.join(d, n)) for n in names)


def maybe_enable() -> bool:
    """Import-time hook: arm the cache iff ``FLAGS_compile_cache`` is set
    (env-seeded like every flag)."""
    if flag_value("FLAGS_compile_cache"):
        return enable()
    return False
