"""``paddle.save`` / ``paddle.load`` — pickled state persistence.

Analog of the reference's ``python/paddle/framework/io.py`` (save:574,
load:791): nested state dicts of Tensors pickled to disk. Arrays are
converted to numpy on save (device → host once) and restored as Tensors on
load. bfloat16 (no numpy dtype) round-trips via a tagged uint16 view.
"""
from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np

from .tensor import Parameter, Tensor

__all__ = ["save", "load"]

_BF16_TAG = "__bf16__"


def _to_picklable(obj):
    if isinstance(obj, Tensor):
        arr = obj._data
        if arr.dtype == jnp.bfloat16:
            return {_BF16_TAG: True,
                    "data": np.asarray(arr.view(jnp.uint16)),
                    "name": obj.name}
        return np.asarray(arr)
    if isinstance(obj, jnp.ndarray):
        return _to_picklable(Tensor(obj))
    if isinstance(obj, dict):
        return {k: _to_picklable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_picklable(v) for v in obj)
    return obj


def _from_picklable(obj):
    if isinstance(obj, dict):
        if obj.get(_BF16_TAG):
            arr = jnp.asarray(obj["data"]).view(jnp.bfloat16)
            return Tensor(arr, stop_gradient=True)
        return {k: _from_picklable(v) for k, v in obj.items()}
    if isinstance(obj, np.ndarray):
        return Tensor(jnp.asarray(obj), stop_gradient=True)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_picklable(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_picklable(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        return _from_picklable(pickle.load(f))
