"""Retrace-cause tracking: records WHY a compiled function re-traced.

Reference analog: the reference caches compiled programs per
(op, attrs, var shapes) and a miss is silent — the first visible symptom
of signature churn is a slow step. Here every trace site (an eager-op
jit wrapper in framework/dispatch.py, the hapi donated train step)
registers the signature it was traced with; a SECOND trace at the same
site diffs the new signature against the last one and classifies the
cause:

* ``shape``      — same leaf structure/dtypes, at least one shape changed
                   (the "bucket your variable-length data" class);
* ``dtype``      — a leaf dtype changed (e.g. f32 batch after bf16 warmup);
* ``structure``  — leaf count / tree structure changed;
* ``static_arg`` — a static (non-array) argument changed, keyed by which
                   component: the hapi step reports its frozen-parameter
                   set as the ``frozen_set`` cause (progressive unfreezing
                   re-traces are expected — but a flapping frozen set is a
                   compile storm).

Counters (framework/monitor.py): ``dispatch/retrace_cause`` (total) and
``dispatch/retrace_cause/<cause>``, surfaced by ``bench.py --dry-run``
and consumed by the recompile-churn analysis pass
(paddle_tpu/analysis/passes.py), which turns per-site churn into
Findings with thresholds.

Cost model: ``record`` runs only when the wrapped python function body
executes — for a jitted function that is trace time, never the compiled
hot path. Site bookkeeping takes a lock; traces are orders of magnitude
rarer than dispatches (same argument as profiler/span.py).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

from .monitor import stat_add

__all__ = ["site", "snapshot", "reset"]

_lock = threading.Lock()
_sites: Dict[str, "_Site"] = {}
# registry bound: hapi allocates one site per Model instance (and a
# serving engine several per bucket), so a sweep/notebook creating
# thousands of Models must not grow host memory (and snapshot() cost)
# without bound. Past the cap site() returns an UNREGISTERED _Site:
# counting still works for callers that hold the returned site by
# reference across traces (dispatch closures, the Model._probe_site
# attribute) — only snapshot() visibility is bounded. The cap is sized
# well above what a test-suite-scale process accumulates (~500 sites at
# ISSUE 10): a run that crosses it silently drops NEW sites from
# snapshot(), which reads as "this engine never traced" to the
# one-trace-per-bucket assertions — a cliff that must stay far from
# normal use. ~100 bytes per site: 4096 is still nothing.
_MAX_SITES = 4096


class _Site:
    """One trace location: last signature + per-cause retrace counts."""

    __slots__ = ("name", "last_sig", "last_static", "traces", "causes")

    def __init__(self, name: str):
        self.name = name
        self.last_sig: Optional[Tuple] = None
        self.last_static: Optional[Any] = None
        self.traces = 0
        self.causes: Dict[str, int] = {}

    def record(self, sig: Tuple, static_key: Any = None) -> Optional[str]:
        """Register one trace of this site. ``sig`` is a tuple of
        (shape, dtype) leaf descriptors; ``static_key`` is a dict of
        named static components (the differing NAME becomes the cause
        when it is a known one). Returns the classified cause, or None
        for the site's first trace."""
        with _lock:
            self.traces += 1
            if self.traces == 1:
                self.last_sig, self.last_static = sig, static_key
                return None
            cause = _classify(self.last_sig, sig,
                              self.last_static, static_key)
            self.last_sig, self.last_static = sig, static_key
            self.causes[cause] = self.causes.get(cause, 0) + 1
        stat_add("dispatch/retrace_cause")
        stat_add(f"dispatch/retrace_cause/{cause}")
        return cause


def _classify(old_sig, new_sig, old_static, new_static) -> str:
    if old_static != new_static:
        if isinstance(old_static, dict) and isinstance(new_static, dict):
            for k in old_static:
                if new_static.get(k, old_static[k]) != old_static[k]:
                    # a named static component (e.g. "frozen_set") IS the
                    # cause label when it diffs
                    return k if k in _NAMED_CAUSES else "static_arg"
        return "static_arg"
    if old_sig == new_sig:
        # same signature re-traced: the wrapper identity changed (cache
        # cleared / rebuilt fn) — still a compile, still worth counting
        return "rebuild"
    old_leaves, new_leaves = list(old_sig), list(new_sig)
    if len(old_leaves) != len(new_leaves):
        return "structure"
    dtype_diff = any(o[1] != n[1] for o, n in zip(old_leaves, new_leaves))
    if dtype_diff:
        return "dtype"
    return "shape"


_NAMED_CAUSES = frozenset({"frozen_set", "n_inputs"})


def site(name: str) -> _Site:
    """Get-or-create the named trace site.

    Site granularity is deliberate: ``op/<name>`` sites are shared
    across attrs variants and callers — every compile of the logical op
    beyond its first IS the churn the counters exist to expose (a
    thousand distinct ``scale`` attrs = a thousand XLA compiles of one
    op, the jit-cache-exhaustion bug class), classified by WHAT changed.
    Per-caller baselines (the hapi per-Model sites) are for steps whose
    signature is expected stable."""
    with _lock:
        s = _sites.get(name)
        if s is None:
            s = _Site(name)
            if len(_sites) < _MAX_SITES:
                _sites[name] = s
        return s


def sig_of(arrays) -> Tuple:
    """(shape, dtype) leaf descriptors for a flat sequence of arrays or
    tracers (both expose .shape/.dtype during trace)."""
    out = []
    for a in arrays:
        shape = tuple(getattr(a, "shape", ()))
        dtype = str(getattr(a, "dtype", type(a).__name__))
        out.append((shape, dtype))
    return tuple(out)


def snapshot() -> Dict[str, dict]:
    """Per-site view for the recompile-churn analysis pass."""
    with _lock:
        return {name: {"traces": s.traces, "causes": dict(s.causes)}
                for name, s in _sites.items()}


def reset() -> None:
    """Zero all site counts IN PLACE: built jit wrappers hold their
    _Site by reference, so dropping the registry entries would orphan
    them — their later traces would never reach snapshot()."""
    with _lock:
        for s in _sites.values():
            s.last_sig = None
            s.last_static = None
            s.traces = 0
            s.causes = {}
