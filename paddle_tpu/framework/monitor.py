"""Runtime counters + per-op timing (observability).

Reference: paddle/fluid/platform/monitor.h:78 (``StatRegistry`` /
``STAT_ADD`` — process-wide named int counters, e.g. GPU mem stats in
memory/stats.cc) and the ``benchmark`` flag that prints per-op timing
(platform/flags.cc).

The dispatch layer feeds two families automatically:
  * ``op_count/<name>`` — calls per op (always on, ~free);
  * ``op_time_ms/<name>`` — accumulated wall ms per op when
    ``FLAGS_benchmark`` is set (forces a block_until_ready per call, so
    ONLY for debugging — it serializes the device).
"""
from __future__ import annotations

import threading
from typing import Dict

__all__ = ["stat_add", "stat_get", "stat_reset", "stats_summary",
           "all_stats"]

_lock = threading.Lock()
_stats: Dict[str, float] = {}


def stat_add(name: str, value: float = 1) -> None:
    """STAT_ADD analog (monitor.h:131).

    Lock-free on the hot path: a racing pair of threads may lose an
    increment, which is acceptable for observability counters — taking a
    lock per eager op dispatch is not."""
    _stats[name] = _stats.get(name, 0) + value


def stat_get(name: str) -> float:
    with _lock:
        return _stats.get(name, 0)


def stat_reset(name: str = None) -> None:
    with _lock:
        if name is None:
            _stats.clear()
        else:
            _stats.pop(name, None)


def all_stats() -> Dict[str, float]:
    with _lock:
        return dict(_stats)


def stats_summary(prefix: str = "") -> str:
    """Human-readable counter table (≙ StatRegistry::publish)."""
    rows = sorted((k, v) for k, v in all_stats().items()
                  if k.startswith(prefix))
    if not rows:
        return "(no stats)"
    w = max(len(k) for k, _ in rows)
    return "\n".join(f"{k:<{w}}  {v:g}" for k, v in rows)
