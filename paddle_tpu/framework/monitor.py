"""Runtime counters + value distributions (observability).

Reference: paddle/fluid/platform/monitor.h:78 (``StatRegistry`` /
``STAT_ADD`` — process-wide named int counters, e.g. GPU mem stats in
memory/stats.cc) and the ``benchmark`` flag that prints per-op timing
(platform/flags.cc).

Two stat families:

* **counters** (``stat_add``/``stat_get``) — monotonically accumulated
  floats, e.g. ``op_count/<name>`` (calls per op, always on, ~free),
  ``op_cache_hit``/``op_cache_miss`` (jit executable cache), and
  ``hapi/host_sync`` (device→host flushes in ``Model.fit`` — the async
  fast path's sync budget, asserted at O(steps/log_freq) by tests and
  ``bench.py --dry-run`` rather than assumed);
* **histograms** (``stat_observe``/``stat_histogram``) — value
  distributions with count/sum/min/max and p50/p95/p99 over a bounded
  reservoir, e.g. ``op_time_ms/<name>`` (per-call wall ms when
  ``FLAGS_benchmark`` is set — forces a block_until_ready per call, so
  ONLY for debugging: it serializes the device) and
  ``hapi/step_time_ms`` (host wall time per train step, always on).

THREADING CONTRACT (the one place it is stated): writers —
``stat_add``/``stat_observe`` — are lock-free on the hot path; a racing
pair of threads may lose an increment or a sample, which is acceptable
for observability and the reason taking a lock per eager op dispatch is
not. Readers — ``stat_get``/``stat_histogram``/``all_stats``/
``all_histograms``/``stats_summary`` — take ``_lock`` and copy, so they
never observe a dict mid-resize; values they return are a consistent
snapshot only to within that writer race. ``stat_reset`` also locks.
The reservoir append rides on deque's GIL-atomic append, bounded by
``maxlen`` so a hot histogram cannot grow without bound.

The richer span profiler (nesting, chrome-trace export) lives in
``paddle_tpu/profiler/span.py`` and exports these stats alongside its
spans in one Prometheus exposition.
"""
from __future__ import annotations

import math
import threading
from collections import deque
from typing import Dict, Optional

__all__ = ["stat_add", "stat_get", "stat_reset", "stats_summary",
           "all_stats", "stat_observe", "stat_histogram", "all_histograms",
           "histogram_samples"]

_lock = threading.Lock()
_stats: Dict[str, float] = {}
_RESERVOIR = 4096
_hists: Dict[str, "_Hist"] = {}


def stat_add(name: str, value: float = 1) -> None:
    """STAT_ADD analog (monitor.h:131). Lock-free writer — see the
    threading contract in the module docstring."""
    _stats[name] = _stats.get(name, 0) + value


def stat_get(name: str) -> float:
    """Counter value; for a histogram name, its accumulated sum (so code
    written against the old ``op_time_ms`` counter keeps reading a
    meaningful total now that timings are distributions)."""
    with _lock:
        if name in _stats:
            return _stats[name]
        h = _hists.get(name)
        return h.total if h is not None else 0


def stat_reset(name: str = None) -> None:
    with _lock:
        if name is None:
            _stats.clear()
            _hists.clear()
        else:
            _stats.pop(name, None)
            _hists.pop(name, None)


def all_stats() -> Dict[str, float]:
    with _lock:
        return dict(_stats)


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------

class _Hist:
    __slots__ = ("count", "total", "vmin", "vmax", "ring")

    def __init__(self, maxlen: int = _RESERVOIR):
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self.ring = deque(maxlen=maxlen)


def stat_observe(name: str, value: float) -> None:
    """Record one sample into the named distribution. Lock-free writer
    (module-docstring contract); creation of a new histogram is the only
    locked step, paid once per name."""
    h = _hists.get(name)
    if h is None:
        with _lock:
            h = _hists.setdefault(name, _Hist())
    value = float(value)
    h.count += 1
    h.total += value
    if value < h.vmin:
        h.vmin = value
    if value > h.vmax:
        h.vmax = value
    h.ring.append(value)


def _percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an already-sorted sample."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   math.ceil(q * len(sorted_vals)) - 1))
    return sorted_vals[k]


def stat_histogram(name: str) -> Optional[dict]:
    """Summary of a distribution: count/sum/min/max + p50/p95/p99
    (percentiles over the bounded reservoir — exact until ``count``
    exceeds the reservoir size, then over the most recent samples)."""
    with _lock:
        h = _hists.get(name)
        if h is None or h.count == 0:
            return None
        vals = sorted(h.ring)
        return {"count": h.count, "sum": h.total, "min": h.vmin,
                "max": h.vmax, "p50": _percentile(vals, 0.5),
                "p95": _percentile(vals, 0.95),
                "p99": _percentile(vals, 0.99)}


def histogram_samples(name: str) -> list:
    """Copy of a distribution's bounded reservoir (most recent samples,
    oldest first). The sanctioned way for read-side layers — the
    metrics registry bucketizing a monitor distribution, a fleet
    pooling latency reservoirs — to reach raw samples without touching
    ``_hists`` (the monitor-lock-contract self-lint bans that)."""
    with _lock:
        h = _hists.get(name)
        return list(h.ring) if h is not None else []


def all_histograms() -> Dict[str, dict]:
    with _lock:
        names = list(_hists)
    out = {}
    for n in names:
        h = stat_histogram(n)
        if h is not None:
            out[n] = h
    return out


def stats_summary(prefix: str = "") -> str:
    """Human-readable table of counters and distributions
    (≙ StatRegistry::publish)."""
    rows = [(k, f"{v:g}") for k, v in all_stats().items()
            if k.startswith(prefix)]
    rows += [(k, f"n={h['count']} sum={h['sum']:g} p50={h['p50']:g} "
                 f"p95={h['p95']:g} p99={h['p99']:g} max={h['max']:g}")
             for k, h in all_histograms().items() if k.startswith(prefix)]
    rows.sort()
    if not rows:
        return "(no stats)"
    w = max(len(k) for k, _ in rows)
    return "\n".join(f"{k:<{w}}  {v}" for k, v in rows)
